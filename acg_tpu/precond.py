"""Preconditioning subsystem: PCG / pipelined-PCG across the solver tiers.

The reference aCG suite (and this reproduction until now) solves Ax=b
with UNpreconditioned classic and Ghysels-Vanroose pipelined CG -- but
the pipelined-CG literature the suite builds on is explicitly a
*preconditioned* method: both the deep-pipelines formulation
(arXiv:1801.04728) and the global-reduction-pipelining work
(arXiv:1905.06850) interleave the preconditioner apply with the hidden
reductions.  On ill-conditioned systems (the anisotropic/stretched
Poisson family, ``io.generators.aniso_poisson2d_coo``) iteration count,
not seconds/iteration, dominates wall-clock -- so M^-1 is the single
biggest lever left after the kernel tiers.

Three implementations, all of which stay inside the jitted loop carry
(state rides the solve programs as ARGUMENTS, the apply is traced into
the loop body -- no host round-trips, no extra dispatches):

* **Jacobi** (``--precond jacobi``): inverse-diagonal scaling.  The
  diagonal is extracted ONCE at setup from the local DIA/ELL/COO/binned
  planes (:func:`acg_tpu.ops.spmv.matrix_diagonal`; host numpy from the
  stacked per-part blocks on the explicit distributed path) -- zero
  extra communication, one elementwise multiply per apply.
* **block-Jacobi** (``--precond bjacobi[:BS]``): dense Cholesky factors
  of the BS x BS diagonal blocks of the (local) matrix, factored once
  at setup, applied as batched forward/back triangular solves --
  embarrassingly parallel across rows and across the mesh (blocks never
  cross a partition boundary on the distributed tiers), no halo
  traffic.  Zero diagonal entries (stacked-layout padding rows) are
  replaced by identity rows so the factorization stays defined.
* **Chebyshev polynomial** (``--precond cheby:K``): z = p_K(A) r with
  p_K the degree-K Chebyshev approximation of 1/lambda on
  ``[lambda_max / CHEBY_RATIO, CHEBY_SAFETY * lambda_max]``.  Each
  apply is exactly K SpMV applications REUSING the tier's existing SpMV
  + halo-exchange machinery -- the communication pattern is identical
  to K extra SpMVs, which is exactly what the pipelined tier is built
  to hide.  lambda_max comes from a power iteration at setup (run
  through the same SpMV selection the solve programs use).

Disarmament contract (the telemetry/faults/perfmodel discipline):
``--precond none`` programs lower BYTE-IDENTICAL to a build without
this module -- the precond spec is a static jit argument and the
``mstate`` pytree argument is None/absent when disarmed (pinned in
tests/test_hlo_structure.py).

SPD caveat the breakdown path guards: PCG requires M SPD.  A non-SPD M
(or a fault-injected ``precond:`` poison, acg_tpu.faults) surfaces as a
non-finite or NEGATIVE (r, z) scalar, which the detecting loops flag as
a breakdown; the recovery driver then preserves -- or, when the state
itself went non-finite, rebuilds -- the preconditioner state across the
restart (:func:`refresh_state`).
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Chebyshev interval policy: the spectrum is assumed inside
# [lmax / CHEBY_RATIO, CHEBY_SAFETY * lmax].  RATIO 30 is the standard
# smoother heuristic (hypre/AMG practice); SAFETY 1.05 absorbs the power
# iteration's systematic underestimate so p_K stays positive on the
# whole spectrum (a lambda above the interval would make p_K(A)
# indefinite -- exactly the breakdown the detecting loops guard).
CHEBY_RATIO = 30.0
CHEBY_SAFETY = 1.05
POWER_ITERS = 24
DEFAULT_BLOCK = 32

KINDS = ("jacobi", "bjacobi", "cheby")


@dataclasses.dataclass(frozen=True)
class PrecondSpec:
    """One parsed preconditioner selection: immutable and hashable, so
    it rides the solve programs' STATIC jit arguments (the FaultSpec
    design) -- a given spec compiles its own cache entry and ``None``
    compiles the byte-identical unpreconditioned program."""

    kind: str                 # "jacobi" | "bjacobi" | "cheby"
    degree: int = 0           # cheby: SpMVs per apply
    block: int = DEFAULT_BLOCK  # bjacobi: dense block size

    def __str__(self) -> str:
        if self.kind == "cheby":
            return f"cheby:{self.degree}"
        if self.kind == "bjacobi":
            return f"bjacobi:{self.block}"
        return self.kind


def parse_precond(text) -> PrecondSpec | None:
    """``none | jacobi | bjacobi[:BS] | cheby:K`` -> spec (None = off).
    Raises ``ValueError`` naming the offending token."""
    if text is None or isinstance(text, PrecondSpec):
        return text
    t = str(text).strip()
    if t in ("", "none"):
        return None
    fields = t.split(":")
    kind = fields[0]
    if kind == "jacobi":
        if len(fields) != 1:
            raise ValueError(f"precond spec {text!r}: jacobi takes no "
                             f"parameter")
        return PrecondSpec(kind="jacobi")
    if kind == "bjacobi":
        if len(fields) > 2:
            raise ValueError(f"precond spec {text!r}: expected "
                             f"bjacobi[:BLOCKSIZE]")
        bs = DEFAULT_BLOCK
        if len(fields) == 2:
            try:
                bs = int(fields[1])
            except ValueError:
                raise ValueError(f"precond spec {text!r}: bad block size "
                                 f"{fields[1]!r}")
            if bs < 1 or bs > 1024:
                raise ValueError(f"precond spec {text!r}: block size must "
                                 f"be in [1, 1024]")
        return PrecondSpec(kind="bjacobi", block=bs)
    if kind == "cheby":
        if len(fields) != 2:
            raise ValueError(f"precond spec {text!r}: cheby needs a "
                             f"degree (e.g. cheby:4)")
        try:
            k = int(fields[1])
        except ValueError:
            raise ValueError(f"precond spec {text!r}: bad degree "
                             f"{fields[1]!r}")
        if k < 1 or k > 64:
            raise ValueError(f"precond spec {text!r}: cheby degree must "
                             f"be in [1, 64]")
        return PrecondSpec(kind="cheby", degree=k)
    raise ValueError(f"precond spec {text!r}: unknown kind {kind!r} "
                     f"(none, jacobi, bjacobi[:BS], cheby:K)")


# -- device-side state builders (single-program tiers) --------------------

def jacobi_state(A, sdt):
    """``(dinv,)``: the inverse diagonal in the scalar dtype, extracted
    on device (zero transfers).  Zero diagonal entries -- structural
    padding rows of the stacked layouts -- invert to 0, so padded
    residual entries (exactly 0 by construction) stay exactly 0."""
    import jax
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import matrix_diagonal

    @jax.jit
    def build(A):
        d = matrix_diagonal(A).astype(sdt)
        return (jnp.where(d != 0, 1.0 / jnp.where(d != 0, d, 1.0),
                          jnp.zeros_like(d)),)

    return build(A)


def _dia_diag_blocks(planes, offsets, n: int, bs: int, sdt):
    """(nb, bs, bs) dense diagonal blocks of square DIA planes, built on
    device by one scatter per in-band offset (|off| < bs; wider offsets
    cannot land inside a bs x bs diagonal block)."""
    import jax.numpy as jnp

    nb = -(-n // bs)
    blocks = jnp.zeros((nb, bs, bs), dtype=sdt)
    rows = jnp.arange(n)
    bi = rows // bs
    i = rows % bs
    for plane, off in zip(planes, offsets):
        if abs(int(off)) >= bs:
            continue
        j = i + int(off)
        valid = (j >= 0) & (j < bs) & (rows + int(off) >= 0) \
            & (rows + int(off) < n)
        blocks = blocks.at[bi, i, jnp.clip(j, 0, bs - 1)].add(
            jnp.where(valid, plane[:n].astype(sdt), 0.0))
    return blocks


def _gather_diag_blocks(rows, cols, vals, n: int, bs: int, sdt):
    """(nb, bs, bs) diagonal blocks from flat (row, col, val) triples
    (the ELL/COO/binned gather formats flattened); entries outside the
    block diagonal contribute nothing."""
    import jax.numpy as jnp

    nb = -(-n // bs)
    blocks = jnp.zeros((nb, bs, bs), dtype=sdt)
    bi = rows // bs
    i = rows % bs
    j = cols - bi * bs
    valid = (j >= 0) & (j < bs) & (rows < n)
    return blocks.at[bi, i, jnp.clip(j, 0, bs - 1)].add(
        jnp.where(valid, vals.astype(sdt), 0.0))


def diag_blocks(A, bs: int, sdt):
    """(nb, bs, bs) dense diagonal blocks of any device matrix format,
    with identity substituted on empty-diagonal rows (padding) so the
    Cholesky below stays defined."""
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import (BinnedEllMatrix, CooMatrix, DiaMatrix,
                                  EllMatrix)

    n = A.nrows
    if isinstance(A, DiaMatrix):
        blocks = _dia_diag_blocks(A.data, A.offsets, n, bs, sdt)
    elif isinstance(A, EllMatrix):
        rows = jnp.repeat(jnp.arange(n), A.data.shape[1])
        blocks = _gather_diag_blocks(rows, A.cols.reshape(-1),
                                     A.data.reshape(-1), n, bs, sdt)
    elif isinstance(A, CooMatrix):
        blocks = _gather_diag_blocks(A.rows, A.cols, A.vals, n, bs, sdt)
    elif isinstance(A, BinnedEllMatrix):
        blocks = jnp.zeros((-(-n // bs), bs, bs), dtype=sdt)
        for brows, bdata, bcols in zip(A.bin_rows, A.bin_data, A.bin_cols):
            K = bdata.shape[1]
            rr = jnp.repeat(brows, K)
            blocks = blocks + _gather_diag_blocks(
                rr, bcols.reshape(-1), bdata.reshape(-1), n, bs, sdt)
        if A.tail_rows.size:
            blocks = blocks + _gather_diag_blocks(
                A.tail_rows, A.tail_cols, A.tail_vals, n, bs, sdt)
    else:
        raise TypeError(f"unsupported device matrix {type(A)}")
    ar = jnp.arange(bs)
    dblk = blocks[:, ar, ar]
    return blocks.at[:, ar, ar].add(jnp.where(dblk == 0, 1.0, 0.0))


def bjacobi_state(A, bs: int, sdt):
    """``(chol,)``: batched lower Cholesky factors of the bs x bs
    diagonal blocks.  A non-SPD block leaves NaNs in its factor, which
    the first apply propagates into (r, z) -- the breakdown path, by
    design, rather than a silent wrong answer."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def build(A):
        return (jnp.linalg.cholesky(diag_blocks(A, bs, sdt)),)

    return build(A)


def estimate_lmax(spmv_fn, A, n: int, sdt, iters: int = POWER_ITERS,
                  seed: int = 0):
    """Power-iteration largest-eigenvalue estimate, run through the
    SAME SpMV selection the solve programs dispatch (so the sharded
    roll tiers estimate over exactly the operator they iterate).
    Returns a device scalar."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(A, key):
        v = jax.random.normal(key, (n,), dtype=sdt)

        def body(_, v):
            w = spmv_fn(A, v.astype(sdt)).astype(sdt)
            return w / jnp.linalg.norm(w)

        v = jax.lax.fori_loop(0, iters, body, v)
        w = spmv_fn(A, v).astype(sdt)
        return jnp.vdot(v, w) / jnp.vdot(v, v)

    return run(A, jax.random.key(seed))


def cheby_state(lmax, sdt):
    """``(lmin, lmax)`` device scalars bounding the Chebyshev interval
    (the RATIO/SAFETY policy above)."""
    import jax.numpy as jnp

    lmax = jnp.asarray(lmax, sdt) * jnp.asarray(CHEBY_SAFETY, sdt)
    return (lmax / jnp.asarray(CHEBY_RATIO, sdt), lmax)


def setup_single(spec: PrecondSpec, A, spmv_fn, sdt, A_program=None):
    """Build the state pytree for the single-program tiers
    (JaxCGSolver + the sharded DIA subclass): a tuple of device arrays
    that rides the solve programs as an argument.  ``A_program`` is
    the matrix the PROGRAMS consume when it differs from the clean
    view (the pallas-roll padded twin) -- diagonal/block extraction
    always reads the clean ``A``, the power iteration runs over the
    program's operator."""
    if spec.kind == "jacobi":
        # matrix-free operators reach this through the matrix_diagonal
        # operator hook (analytic stencil diagonal; typed refusal for
        # user operators registered without a diagonal_fn)
        return jacobi_state(A, sdt)
    if spec.kind == "bjacobi":
        from acg_tpu.ops.operator import is_matrix_free
        if is_matrix_free(A):
            from acg_tpu.errors import AcgError, ErrorCode
            raise AcgError(
                ErrorCode.NOT_SUPPORTED,
                "bjacobi factors stored diagonal blocks, which a "
                "matrix-free operator does not have; use --precond "
                "jacobi (analytic diagonal) or cheby:K (applies only)")
        return bjacobi_state(A, spec.block, sdt)
    Ap = A if A_program is None else A_program
    return cheby_state(estimate_lmax(spmv_fn, Ap, A.nrows, sdt), sdt)


# -- the in-loop apply (traced into the solve programs) -------------------

def make_apply(spec: PrecondSpec, spmv_fn):
    """``apply(mstate, A, r) -> z``, a pure jnp function traced into the
    jitted loop body.  ``spmv_fn(A, x)`` is the TIER'S OWN SpMV closure
    (halo exchange included on the mesh tiers), so the Chebyshev apply's
    communication pattern is exactly K extra SpMVs."""
    import jax
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import acc_dtype

    if spec.kind == "jacobi":
        def apply(mstate, A, r):
            (dinv,) = mstate
            return (r.astype(dinv.dtype) * dinv).astype(r.dtype)
        return apply

    if spec.kind == "bjacobi":
        bs = spec.block

        def apply(mstate, A, r):
            (chol,) = mstate
            n = r.shape[0]
            npad = chol.shape[0] * bs
            rp = r.astype(chol.dtype)
            if npad != n:
                rp = jnp.pad(rp, (0, npad - n))
            R = rp.reshape(chol.shape[0], bs, 1)
            y = jax.lax.linalg.triangular_solve(
                chol, R, left_side=True, lower=True)
            z = jax.lax.linalg.triangular_solve(
                chol, y, left_side=True, lower=True, transpose_a=True)
            return z.reshape(-1)[:n].astype(r.dtype)
        return apply

    k = spec.degree

    def apply(mstate, A, r):
        lmin, lmax = mstate
        adt = acc_dtype(r.dtype)
        lmin = lmin.astype(adt)
        lmax = lmax.astype(adt)
        theta = (lmax + lmin) * 0.5
        delta = (lmax - lmin) * 0.5
        sigma = theta / delta
        rho = 1.0 / sigma
        rs = r.astype(adt)
        d = rs / theta
        z = d
        rcur = rs
        # K steps of the Chebyshev semi-iteration on A z = r from z = 0:
        # exactly K SpMVs, the degree-K polynomial in A applied to r
        for _ in range(k):
            rcur = rcur - spmv_fn(A, d.astype(r.dtype)).astype(adt)
            rho_new = 1.0 / (2.0 * sigma - rho)
            d = rho_new * rho * d + (2.0 * rho_new / delta) * rcur
            z = z + d
            rho = rho_new
        return z.astype(r.dtype)
    return apply


def make_apply_batched(spec: PrecondSpec, spmv_multi_fn=None):
    """``apply(mstate, A, R) -> Z`` over a MULTI-COLUMN residual block
    ``R`` of shape ``(n, B)`` -- the preconditioner apply broadcast
    over the batch axis (the batched multi-RHS tier,
    acg_tpu.solvers.batched).

    Jacobi broadcasts the inverse diagonal across columns in one
    elementwise multiply; block-Jacobi reuses the SAME batched
    triangular solves with B right-hand sides per block (the blocked
    reshape gains a trailing column axis); Chebyshev runs its K-step
    semi-iteration on the whole block through ``spmv_multi_fn``
    (default: the single-device multi-vector SpMV) -- K matrix passes
    for ALL B columns, the same amortization as the solve loop's."""
    import jax
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import acc_dtype

    if spec.kind == "jacobi":
        def apply(mstate, A, R):
            (dinv,) = mstate
            return (R.astype(dinv.dtype) * dinv[:, None]).astype(R.dtype)
        return apply

    if spec.kind == "bjacobi":
        bs = spec.block

        def apply(mstate, A, R):
            (chol,) = mstate
            n, nb_cols = R.shape
            npad = chol.shape[0] * bs
            Rp = R.astype(chol.dtype)
            if npad != n:
                Rp = jnp.pad(Rp, ((0, npad - n), (0, 0)))
            Rb = Rp.reshape(chol.shape[0], bs, nb_cols)
            y = jax.lax.linalg.triangular_solve(
                chol, Rb, left_side=True, lower=True)
            z = jax.lax.linalg.triangular_solve(
                chol, y, left_side=True, lower=True, transpose_a=True)
            return z.reshape(npad, nb_cols)[:n].astype(R.dtype)
        return apply

    k = spec.degree
    if spmv_multi_fn is None:
        from acg_tpu.solvers.batched import spmv_multi as spmv_multi_fn

    def apply(mstate, A, R):
        lmin, lmax = mstate
        adt = acc_dtype(R.dtype)
        lmin = lmin.astype(adt)
        lmax = lmax.astype(adt)
        theta = (lmax + lmin) * 0.5
        delta = (lmax - lmin) * 0.5
        sigma = theta / delta
        rho = 1.0 / sigma
        Rs = R.astype(adt)
        d = Rs / theta
        z = d
        rcur = Rs
        for _ in range(k):
            rcur = rcur - spmv_multi_fn(A, d.astype(R.dtype)).astype(adt)
            rho_new = 1.0 / (2.0 * sigma - rho)
            d = rho_new * rho * d + (2.0 * rho_new / delta) * rcur
            z = z + d
            rho = rho_new
        return z.astype(R.dtype)
    return apply


# -- stacked host-side state builders (the explicit distributed tier) -----

def _np_diag_blocks_from_triples(rows, cols, vals, n: int, bs: int,
                                 out: np.ndarray) -> None:
    """Accumulate (row, col, val) triples into ``out`` ((nb, bs, bs)
    f64) wherever they land inside a bs x bs diagonal block."""
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int64)
    vals = np.asarray(vals, np.float64)
    bi = rows // bs
    j = cols - bi * bs
    ok = (rows < n) & (j >= 0) & (j < bs) & (vals != 0)
    np.add.at(out, (bi[ok], (rows % bs)[ok], j[ok]), vals[ok])


def _np_local_block_triples(local, p: int):
    """Flat (rows, cols, vals) of part ``p``'s local block in any
    StackedLocalBlock format (host numpy views; zero-copy where the
    layout allows)."""
    if local.format == "dia":
        n = local.nrows
        rows = np.arange(n, dtype=np.int64)
        rs, cs, vs = [], [], []
        for plane, off in zip(local.arrays, local.offsets):
            cols = rows + int(off)
            ok = (cols >= 0) & (cols < n)
            rs.append(rows[ok])
            cs.append(cols[ok])
            vs.append(np.asarray(plane[p], np.float64)[ok])
        return (np.concatenate(rs), np.concatenate(cs),
                np.concatenate(vs))
    if local.format == "ell":
        data, cols = local.arrays
        n, K = data.shape[1], data.shape[2]
        rows = np.repeat(np.arange(n, dtype=np.int64), K)
        return rows, np.asarray(cols[p], np.int64).reshape(-1), \
            np.asarray(data[p], np.float64).reshape(-1)
    # binnedell
    bin_rows, bin_data, bin_cols, t_rows, t_cols, t_vals = local.arrays
    rs, cs, vs = [], [], []
    for br, bd, bc in zip(bin_rows, bin_data, bin_cols):
        K = bd.shape[2]
        rs.append(np.repeat(np.asarray(br[p], np.int64), K))
        cs.append(np.asarray(bc[p], np.int64).reshape(-1))
        vs.append(np.asarray(bd[p], np.float64).reshape(-1))
    rs.append(np.asarray(t_rows[p], np.int64))
    cs.append(np.asarray(t_cols[p], np.int64))
    vs.append(np.asarray(t_vals[p], np.float64))
    return np.concatenate(rs), np.concatenate(cs), np.concatenate(vs)


def stacked_jacobi_state(prob, sdt) -> tuple:
    """``(dinv,)`` with dinv (nparts, nmax_owned) host numpy for the
    explicit distributed tier: the diagonal of each part's LOCAL block
    (diagonal entries are owned x owned by construction -- the ghost
    block never holds them), inverted with the zero guard.  Non-owned
    parts of a multi-controller build stay zero: their shards are never
    read by this controller."""
    local = prob.local
    n = local.nrows
    dinv = np.zeros((prob.nparts, n), dtype=np.dtype(sdt))
    owned = (range(prob.nparts) if prob.owned_parts is None
             else prob.owned_parts)
    if local.format == "matfree":
        # the operator-path twin: the ANALYTIC stencil diagonal (host
        # numpy of the same rounded values the device generates),
        # sliced per part -- no stored planes exist to scan
        dglob = prob.operator.host_diagonal()
        for p in owned:
            s = prob.subs[p]
            gids = np.asarray(s.global_ids[: s.nowned], np.int64)
            d = dglob[gids]
            nz = d != 0
            dinv[p, : s.nowned][nz] = 1.0 / d[nz]
        return (dinv,)
    for p in owned:
        rows, cols, vals = _np_local_block_triples(local, p)
        d = np.zeros(n, np.float64)
        on_diag = rows == cols
        np.add.at(d, rows[on_diag], vals[on_diag])
        nz = d != 0
        dinv[p, nz] = 1.0 / d[nz]
    return (dinv,)


def stacked_bjacobi_state(prob, bs: int, sdt) -> tuple:
    """``(chol,)`` with chol (nparts, nb, bs, bs) host numpy: dense
    Cholesky factors of each part's local diagonal blocks (padding /
    non-owned rows become identity blocks).  numpy raises on a non-SPD
    owned block -- surfaced as a typed refusal at setup rather than
    NaNs mid-solve (host setup CAN check, unlike the on-device path)."""
    from acg_tpu.errors import AcgError, ErrorCode

    local = prob.local
    if local.format == "matfree":
        raise AcgError(
            ErrorCode.NOT_SUPPORTED,
            "bjacobi factors stored local diagonal blocks, which the "
            "matrix-free tier does not have; use --precond jacobi "
            "(analytic diagonal) or cheby:K (applies only)")
    n = local.nrows
    nb = -(-n // bs)
    chol = np.zeros((prob.nparts, nb, bs, bs), dtype=np.dtype(sdt))
    eye = np.eye(bs)
    owned = (range(prob.nparts) if prob.owned_parts is None
             else prob.owned_parts)
    for p in range(prob.nparts):
        if p not in owned:
            chol[p] = eye  # never read; keep the factor well-defined
            continue
        blocks = np.zeros((nb, bs, bs), np.float64)
        rows, cols, vals = _np_local_block_triples(local, p)
        _np_diag_blocks_from_triples(rows, cols, vals, n, bs, blocks)
        dblk = np.einsum("bii->bi", blocks)
        empty = dblk == 0
        np.einsum("bii->bi", blocks)[...] = np.where(empty, 1.0, dblk)
        try:
            chol[p] = np.linalg.cholesky(blocks)
        except np.linalg.LinAlgError:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"bjacobi:{bs}: a diagonal block of part {p} is not "
                f"positive definite -- the matrix (or this block size) "
                f"does not admit a block-Jacobi Cholesky")
    return (chol,)


# -- accounting (perfmodel / stats integration) ---------------------------

def flops_per_apply(spec: PrecondSpec, n: int, spmv_flops: float) -> float:
    """Analytic flops of ONE M^-1 apply (the reference's counting
    conventions: 2n per vector op, 3 per stored nonzero per SpMV)."""
    if spec.kind == "jacobi":
        return float(n)
    if spec.kind == "bjacobi":
        # two triangular solves over nb blocks of bs^2/2 entries each
        return 2.0 * n * spec.block
    return spec.degree * (float(spmv_flops) + 8.0 * n)


def bytes_per_apply(spec: PrecondSpec, n: int, vec_bytes: int,
                    mat_bytes_per_spmv: float, state_bytes: float) -> float:
    """Analytic HBM traffic of one apply: state read + vector passes
    (+ the K SpMV passes for cheby)."""
    if spec.kind == "jacobi":
        return state_bytes + 2.0 * n * vec_bytes
    if spec.kind == "bjacobi":
        return state_bytes + 2.0 * n * vec_bytes
    return spec.degree * (mat_bytes_per_spmv + 6.0 * n * vec_bytes)


def state_bytes(mstate) -> int:
    """Total bytes of a state pytree (host or device leaves)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(mstate):
        dt = np.dtype(getattr(leaf, "dtype", np.float64))
        total += int(np.prod(np.shape(leaf))) * dt.itemsize
    return total


def comm_contribution(spec: PrecondSpec | None) -> dict:
    """The static comm-ledger stanza for one preconditioner: how many
    extra halo'd SpMV-equivalents each iteration performs.  Jacobi and
    block-Jacobi are strictly local (the whole point); cheby multiplies
    the halo pattern by its degree."""
    if spec is None:
        return {}
    extra = spec.degree if spec.kind == "cheby" else 0
    return {"kind": str(spec), "applies_per_iteration": 1,
            "halo_spmv_equivalents_per_apply": extra}


def state_finite(mstate) -> bool:
    """True when every leaf of the state pytree is finite -- the
    recovery driver's preserve-vs-rebuild predicate."""
    import jax
    import jax.numpy as jnp

    for leaf in jax.tree_util.tree_leaves(mstate):
        if not bool(jnp.isfinite(jnp.asarray(leaf)).all()):
            return False
    return True


def partition_sensitive(spec) -> bool:
    """True when the preconditioner OPERATOR depends on the row
    partition (bjacobi factors the LOCAL diagonal blocks on the
    distributed tier, so M changes when the partition does).  The
    repartition-resume path (acg_tpu.checkpoint) warns on these:
    continuing a PCG recurrence under a different M is flexible-CG
    territory -- it converges, but the short recurrence is no longer
    exactly conjugate.  Jacobi and Chebyshev are partition-invariant
    (diagonal / SpMV polynomial of the global operator)."""
    return spec is not None and getattr(spec, "kind", None) == "bjacobi"


def refresh_state(solver, driver) -> bool:
    """Recovery hook (solvers' restart loops): PRESERVE the
    preconditioner state across a restart when it is still finite --
    the state is immutable, so a numerical breakdown cannot have
    corrupted it -- and REBUILD it from the matrix when it is not
    (e.g. a non-SPD block factored to NaN, or operator-poisoned state).
    Returns True when a rebuild happened; every decision lands in the
    recovery log."""
    spec = getattr(solver, "precond_spec", None)
    if spec is None or getattr(solver, "_mstate", None) is None:
        return False
    if state_finite(solver._mstate):
        driver.record(f"preconditioner ({spec}) state preserved across "
                      f"restart")
        return False
    solver._mstate = None
    solver._ensure_precond_state()
    driver.record(f"preconditioner ({spec}) state non-finite; rebuilt "
                  f"from the matrix", kind="recovery")
    return True


# -- host (numpy/scipy) twins: the eager solver + the test oracle ---------

class HostPrecond:
    """Eager numpy preconditioner for the host reference solver (and
    the scipy-checked oracle the device applies are tested against).
    Same three kinds, same interval policy, f64 arithmetic."""

    def __init__(self, spec: PrecondSpec, csr):
        import scipy.sparse as sp

        self.spec = spec
        csr = sp.csr_matrix(csr)
        n = csr.shape[0]
        if spec.kind == "jacobi":
            d = csr.diagonal().astype(np.float64)
            dinv = np.zeros_like(d)
            dinv[d != 0] = 1.0 / d[d != 0]
            self.state = (dinv,)
        elif spec.kind == "bjacobi":
            bs = spec.block
            nb = -(-n // bs)
            blocks = np.zeros((nb, bs, bs), np.float64)
            coo = csr.tocoo()
            _np_diag_blocks_from_triples(coo.row, coo.col, coo.data, n,
                                         bs, blocks)
            dblk = np.einsum("bii->bi", blocks)
            np.einsum("bii->bi", blocks)[...] = np.where(dblk == 0, 1.0,
                                                         dblk)
            self.state = (np.linalg.cholesky(blocks),)
        else:
            rng = np.random.default_rng(0)
            v = rng.standard_normal(n)
            for _ in range(POWER_ITERS):
                w = csr @ v
                v = w / np.linalg.norm(w)
            lmax = float(v @ (csr @ v) / (v @ v)) * CHEBY_SAFETY
            self._csr = csr
            self.state = (lmax / CHEBY_RATIO, lmax)
        self.n = n

    def apply(self, r: np.ndarray) -> np.ndarray:
        spec = self.spec
        if spec.kind == "jacobi":
            return self.state[0] * r
        if spec.kind == "bjacobi":
            import scipy.linalg as sla

            (chol,) = self.state
            bs = spec.block
            npad = chol.shape[0] * bs
            rp = np.zeros(npad)
            rp[: self.n] = r
            out = np.empty_like(rp)
            for b in range(chol.shape[0]):
                out[b * bs:(b + 1) * bs] = sla.cho_solve(
                    (chol[b], True), rp[b * bs:(b + 1) * bs])
            return out[: self.n]
        lmin, lmax = self.state
        theta = (lmax + lmin) * 0.5
        delta = (lmax - lmin) * 0.5
        sigma = theta / delta
        rho = 1.0 / sigma
        d = r / theta
        z = d.copy()
        rcur = r.astype(np.float64).copy()
        for _ in range(spec.degree):
            rcur = rcur - self._csr @ d
            rho_new = 1.0 / (2.0 * sigma - rho)
            d = rho_new * rho * d + (2.0 * rho_new / delta) * rcur
            z = z + d
            rho = rho_new
        return z
