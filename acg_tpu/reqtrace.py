"""Request-scoped observability for the solver service -- the request
observatory.

Everything the earlier observability tiers built (telemetry rings,
``--timeline`` spans, the status document, SLO burn) is SOLVE-scoped:
one CLI invocation, one attribution.  The ``--serve`` daemon answers
REQUESTS, and a request's latency is paid across stages no solve-scoped
plane can see -- admission, queue wait, the coalesce window, cache
lookups, an absorbed compile, its share of a batched solve, demux, and
the handoff back to the waiting client.  This module is the per-request
ledger of exactly that:

* **identity** -- every request resolves to a stable ``request_id``
  (client-supplied ``request_id`` field, the trace-id of a W3C
  ``traceparent``, or generated), echoed in the response body, every
  structured event on the failure path, and the chaos campaign's
  verification rows.

* **stages** -- :class:`RequestRecord` accumulates per-stage seconds
  (:data:`STAGES`), feeds the ``acg_serve_stage_seconds{stage}``
  histogram, and drops ``cat="request"`` spans on the PR 8 tracing
  recorder so ``--serve --timeline`` renders the SERVICE timeline: the
  worker's batch row plus one lane per in-flight request window.

* **ledger** -- :class:`RequestLog` appends one ``acg-tpu-access/1``
  JSONL row per completed request (``--access-log FILE``; a single
  ``os.write`` on an ``O_APPEND`` fd, so concurrent completions never
  interleave bytes), keeps the last-K completed documents for ``GET
  /requests``, and tracks in-flight lanes + outcome tallies for the
  ``requests:`` status block.

Host-side stdlib bookkeeping only: nothing here touches a traced
program, so the daemon's lowered programs stay byte-identical with the
observatory armed or not (pinned in tests/test_hlo_structure.py).
"""

from __future__ import annotations

import collections
import json
import os
import re
import sys
import threading
import time

ACCESS_SCHEMA = "acg-tpu-access/1"
REQUESTS_SCHEMA = "acg-serve-requests/1"

# the per-request stage vocabulary, in service order: admission checks,
# queue residency, the coalesce window, operator/program cache lookups,
# the absorbed compile, this request's share of the (possibly batched)
# solve, per-request demux, and the worker->submitter handoff
STAGES = ("admit", "queue-wait", "coalesce", "cache", "compile",
          "solve", "demux", "respond")

# terminal outcomes (the ledger's enum; shed-* is a closed family)
OUTCOMES = ("ok", "shed-queue-full", "shed-slo-burn", "shed-shutdown",
            "deadline-expired", "request-failed", "invalid-request")

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-[0-9a-f]{16}-[0-9a-f]{2}$")
# client-supplied ids: printable ASCII, no whitespace, bounded -- an id
# rides log lines, JSONL rows and trace args verbatim
_ID_RE = re.compile(r"^[\x21-\x7e]{1,128}$")


def parse_traceparent(value) -> str | None:
    """The 32-hex trace-id out of a W3C ``traceparent`` value
    (``00-<trace-id>-<parent-id>-<flags>``), or ``None``."""
    m = _TRACEPARENT_RE.match(str(value or "").strip().lower())
    return m.group(1) if m else None


def generate_request_id() -> str:
    return "req-" + os.urandom(8).hex()


def request_id_from_doc(doc) -> str:
    """Resolve one POST /solve body's request identity: a well-formed
    client ``request_id`` wins, else the trace-id of a ``traceparent``,
    else a generated ``req-...``.  A malformed client id is IGNORED,
    not refused -- identity must never cost a request its answer."""
    if isinstance(doc, dict):
        rid = doc.get("request_id")
        if isinstance(rid, str) and _ID_RE.match(rid):
            return rid
        tid = parse_traceparent(doc.get("traceparent"))
        if tid:
            return tid
    return generate_request_id()


def outcome_of(body) -> str:
    """Map a serve response body to its ledger outcome: green is
    ``ok``, admission/deadline refusals keep their typed kind, request
    validation collapses to ``invalid-request``, and every other typed
    error -- breakdown, non-convergence, the isolation boundary -- is
    ``request-failed``."""
    if isinstance(body, dict):
        if body.get("ok"):
            return "ok"
        kind = str((body.get("error") or {}).get("type") or "")
        if kind.startswith("shed-") or kind == "deadline-expired":
            return kind
        if kind in ("invalid-request", "faults-disabled"):
            return "invalid-request"
    return "request-failed"


class RequestRecord:
    """One request's observability state: per-stage seconds, the
    provenance notes (cache/coalesce/degrade/plan/batch), and the
    timeline lane it occupies while in flight.  The submitter thread
    and the worker both mutate it; every mutation and snapshot rides
    the record lock, and a completed record is frozen (the submit
    waiter and the worker can race at the deadline boundary)."""

    def __init__(self, request_id: str, matrix=None):
        self.request_id = str(request_id)
        self.id: int | None = None
        self.matrix = matrix
        self.arrival = time.monotonic()
        self.lane: int | None = None
        self.outcome: str | None = None
        self._lock = threading.Lock()
        self._stages: collections.OrderedDict = collections.OrderedDict()
        self._notes: dict = {}
        self._row: dict | None = None
        self._done = False

    def stage(self, name: str, seconds: float, t1: float | None = None,
              **attrs) -> None:
        """Account ``seconds`` to stage ``name`` (accumulating), feed
        the ``acg_serve_stage_seconds`` histogram, and drop a
        ``cat="request"`` span on this request's timeline lane.  ``t1``
        is the stage's wall-clock end (``time.time()``); defaults to
        now -- the span recorder wants epoch endpoints, the ledger only
        the duration."""
        from acg_tpu import metrics, tracing
        sec = max(float(seconds), 0.0)
        with self._lock:
            if self._done:
                return
            self._stages[name] = self._stages.get(name, 0.0) + sec
        metrics.record_serve_stage(name, sec)
        end = float(t1) if t1 is not None else time.time()
        tracing.record_span(str(name), end - sec, end, cat="request",
                            lane=self.lane, request=self.request_id,
                            **attrs)

    def note(self, key: str, value) -> None:
        """Attach provenance (cache verdicts, batch membership, plan
        source, ...) that rides the ledger row and /requests doc."""
        with self._lock:
            if not self._done:
                self._notes[key] = value

    def stages(self) -> dict:
        with self._lock:
            return dict(self._stages)

    def doc(self) -> dict:
        """The /requests document: the sealed ledger row for a
        completed request, a live snapshot (so-far stages + lane) for
        an in-flight one."""
        with self._lock:
            if self._row is not None:
                return dict(self._row)
            d = {"request_id": self.request_id, "id": self.id,
                 "matrix": self.matrix, "inflight": True,
                 "lane": self.lane,
                 "wall_seconds_so_far":
                     round(max(time.monotonic() - self.arrival, 0.0), 6),
                 "stages": {k: round(v, 6)
                            for k, v in self._stages.items()}}
            for key in ("cache", "coalesced", "degraded", "plan",
                        "batch"):
                if key in self._notes:
                    d[key] = self._notes[key]
            return d


class RequestLog:
    """The daemon's request registry + access ledger: assigns timeline
    lanes to in-flight requests, keeps a bounded ring of completed
    request documents (``GET /requests``), tallies outcomes, and
    appends one atomic ``acg-tpu-access/1`` JSONL row per completed
    request."""

    def __init__(self, path: str | None = None, ring: int = 64):
        self.path = path
        self._lock = threading.Lock()
        self._inflight: dict[int, RequestRecord] = {}
        self._completed: collections.deque = collections.deque(
            maxlen=max(int(ring), 1))
        self._outcomes: collections.Counter = collections.Counter()
        self._last_done = 0.0
        self._fd: int | None = None
        if path:
            self._fd = os.open(path, os.O_APPEND | os.O_CREAT
                               | os.O_WRONLY, 0o644)

    def begin(self, request_id: str, matrix=None) -> RequestRecord:
        """Open a record: assign the lowest free timeline lane and
        register it in flight."""
        from acg_tpu import metrics
        rec = RequestRecord(request_id, matrix=matrix)
        with self._lock:
            lanes = {r.lane for r in self._inflight.values()}
            lane = 0
            while lane in lanes:
                lane += 1
            rec.lane = lane
            self._inflight[id(rec)] = rec
            n = len(self._inflight)
        metrics.record_serve_inflight(n)
        return rec

    def complete(self, rec: RequestRecord, outcome: str) -> dict | None:
        """Seal ``rec``: freeze its stages, free the lane, move it to
        the completed ring, tally the outcome, and append the ledger
        row in ONE ``os.write`` (atomic for an O_APPEND fd -- rows from
        racing completions never interleave).  Ledger ``t_done`` stamps
        are strictly increasing in file order; ``t_arrival`` is derived
        as ``t_done - wall`` so every row is self-consistent.
        Idempotent: the first completion wins; returns the row (or
        ``None`` on the losing side of the race)."""
        from acg_tpu import metrics
        with rec._lock:
            if rec._done:
                return None
            rec._done = True
            rec.outcome = str(outcome)
            stages = {k: round(float(v), 6)
                      for k, v in rec._stages.items()}
            notes = dict(rec._notes)
        wall = max(time.monotonic() - rec.arrival, 0.0)
        row = {"schema": ACCESS_SCHEMA, "request_id": rec.request_id,
               "id": rec.id, "matrix": rec.matrix,
               "outcome": rec.outcome,
               "wall_seconds": round(wall, 6), "stages": stages}
        for key in ("cache", "coalesced", "degraded", "plan", "batch"):
            if key in notes:
                row[key] = notes[key]
        with self._lock:
            t_done = time.time()
            if t_done <= self._last_done:
                t_done = self._last_done + 1e-6
            self._last_done = t_done
            row["t_done"] = round(t_done, 6)
            row["t_arrival"] = round(t_done - wall, 6)
            with rec._lock:
                rec._row = row
            self._inflight.pop(id(rec), None)
            self._completed.append(rec)
            self._outcomes[rec.outcome] += 1
            n = len(self._inflight)
            if self._fd is not None:
                try:
                    os.write(self._fd,
                             (json.dumps(row) + "\n").encode())
                except OSError as e:
                    sys.stderr.write(f"acg-tpu: --access-log "
                                     f"{self.path}: {e}\n")
        metrics.record_serve_inflight(n)
        return row

    def snapshot(self) -> dict:
        """The ``GET /requests`` document: last-K completed rows plus
        the current in-flight snapshots.  Membership is captured under
        the registry lock, each document under its record lock -- a
        reader under load sees a consistent (never torn) view."""
        with self._lock:
            inflight = list(self._inflight.values())
            completed = list(self._completed)
            outcomes = dict(self._outcomes)
        return {"schema": REQUESTS_SCHEMA,
                "inflight": [r.doc() for r in inflight],
                "completed": [r.doc() for r in completed],
                "outcomes": outcomes}

    def summary(self) -> dict:
        """The status document's ``requests:`` block."""
        with self._lock:
            return {"inflight": len(self._inflight),
                    "completed": sum(self._outcomes.values()),
                    "ring": int(self._completed.maxlen or 0),
                    "outcomes": dict(self._outcomes),
                    "access_log": self.path}

    def close(self) -> None:
        with self._lock:
            fd, self._fd = self._fd, None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
