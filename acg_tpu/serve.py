"""Long-lived solver service (``--serve``) -- the serving half of the
millions-of-users north star.

Every tier in this repo is a BATCH program: each CLI invocation pays
ingest + partition + compile before the first iteration runs, which is
exactly the cost profile the reference suite (PAPER.md) has -- and
exactly what a request-serving fleet cannot afford.  This module turns
the twelve mechanisms into a SYSTEM:

* **daemon**: one process owns the device mesh for its lifetime and
  answers ``POST /solve`` over a stdlib HTTP endpoint (the
  ``--metrics-port`` design: ThreadingHTTPServer, zero dependencies).
  ``GET /status`` serves the observatory status document and ``GET
  /metrics`` the Prometheus exposition, so the PR 4/9 observability
  planes ride the same port.

* **caches**: an *operator cache* (ingested matrix -> device planes /
  partitioned mesh problem, keyed by generator spec x dtype x
  partition) and a *program cache* (constructed solver whose jitted
  programs are compile-warm, keyed by the full recurrence x precond x
  kernels x dtype x nrhs product).  Steady state, a repeated request
  pays ZERO ingest and ZERO compile -- asserted by the
  ``acg_serve_cache_*`` families plus the untouched
  ``acg_compiles_total`` counter (a cache-miss solve runs with
  ``warmup=1`` so its compile is absorbed AND counted; a cache-hit
  solve runs ``warmup=0`` against the warm jit cache).

* **admission control**: a bounded queue sheds with a typed 429-style
  response when full; the PR 9 SLO error-budget burn drives a
  DEGRADE-BEFORE-REFUSE ladder (burn past ``degrade_burn`` serves
  requests on the cheap profile -- classic recurrence, no
  preconditioner -- and marks them ``degraded``; burn past
  ``shed_burn`` sheds outright).  Every request carries a deadline;
  an expired request is answered with a typed 504, never a hang.

* **request isolation**: a breakdown rides the in-solve
  :class:`acg_tpu.solvers.resilience.RecoveryDriver` ladder first;
  what still escapes is caught per request, answered with a TYPED
  error document, retried within a bounded budget, and the possibly
  poisoned program-cache entry is invalidated -- the daemon itself
  never dies to a request.

* **coalescing**: compatible queued requests (same operator, classic
  recurrence, unpreconditioned, same tolerances) merge into ONE
  ``--nrhs B`` batched solve (PR 11) and demux per request -- bitwise
  equal to serving them singly, because the batched-classic recurrence
  is column-wise identical to the single-RHS program (pinned in
  tests/test_batched.py and re-pinned in tests/test_serve.py).

* **self-healing**: the daemon persists its operator-cache key set (a
  small JSON sidecar on the ``--ckpt`` path) after every request; the
  PR 10 supervisor relaunches a crashed daemon, which WARM-RESTORES
  the operator cache from that state before accepting traffic.
  ``--chaos SEED[:N] --serve`` runs the campaign AGAINST the live
  daemon: seeded per-request fault schedules with independent
  host-side answer verification per green response (exit 96 on any
  wrong-answer-green -- the supervisor campaign's acceptance bar).
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time

import numpy as np

from acg_tpu import reqtrace
from acg_tpu.errors import (AcgError, BreakdownError, ExitCode,
                            NotConvergedError)

SCHEMA = "acg-serve/1"
STATE_SCHEMA = "acg-serve-state/1"
# per-request fault specs are only honoured when the daemon was armed
# for them (the chaos campaign's hook) -- a production daemon must not
# be crashable by a request body
FAULTS_ENV = "ACG_TPU_SERVE_FAULTS"
# how long the coalescer waits for compatible followers after the
# first request of a batch is popped
COALESCE_WINDOW_SECS = 0.05


# -- configuration ---------------------------------------------------------

class ServeConfig:
    """Daemon knobs (CLI ``--serve-*`` flags; all defaulted so tests
    can construct one directly)."""

    def __init__(self, *, port: int = 0, queue_depth: int = 16,
                 coalesce: int = 8, default_timeout: float = 60.0,
                 degrade_burn: float = 0.5, shed_burn: float = 0.9,
                 operator_cache_size: int = 4,
                 program_cache_size: int = 16, retries: int = 1,
                 retry_backoff: float = 0.05,
                 state_path: str | None = None,
                 preload: str | None = None, nparts: int = 0,
                 comm: str = "xla", dtype: str = "f64",
                 allow_faults: bool = False, autotune: bool = False,
                 calibration: dict | None = None,
                 access_log: str | None = None,
                 request_ring: int = 64):
        self.port = int(port)
        self.queue_depth = int(queue_depth)
        self.coalesce = int(coalesce)
        self.default_timeout = float(default_timeout)
        self.degrade_burn = float(degrade_burn)
        self.shed_burn = float(shed_burn)
        self.operator_cache_size = int(operator_cache_size)
        self.program_cache_size = int(program_cache_size)
        self.retries = int(retries)
        self.retry_backoff = float(retry_backoff)
        self.state_path = state_path
        self.preload = preload
        self.nparts = int(nparts)
        self.comm = comm
        self.dtype = dtype
        self.allow_faults = bool(allow_faults) \
            or os.environ.get(FAULTS_ENV) == "1"
        # decision observatory (--serve --autotune): plan on operator-
        # cache miss against this calibration, replan when it changes
        self.autotune = bool(autotune)
        self.calibration = calibration
        # request observatory (--access-log): the append-only
        # acg-tpu-access/1 ledger path, and the size of the completed-
        # request ring GET /requests serves
        self.access_log = access_log
        self.request_ring = int(request_ring)


class RequestRefused(Exception):
    """A typed admission/validation refusal: ``kind`` is the machine-
    readable error type, ``status`` the HTTP code it rides."""

    def __init__(self, kind: str, message: str, status: int = 400):
        super().__init__(message)
        self.kind = kind
        self.status = int(status)


class _Request:
    _next_id = [0]
    _id_lock = threading.Lock()
    # request observatory: the stable client-facing identity and the
    # RequestRecord tracking it -- attached by submit() right after
    # construction (class defaults keep direct constructions safe)
    request_id: str | None = None
    trace: "reqtrace.RequestRecord | None" = None

    def __init__(self, doc: dict, cfg: ServeConfig):
        with self._id_lock:
            self._next_id[0] += 1
            self.id = self._next_id[0]
        self.matrix = doc.get("matrix") or cfg.preload
        if not self.matrix:
            raise RequestRefused(
                "invalid-request", "no 'matrix' in the request and the "
                "daemon was started without a preload operator")
        if not str(self.matrix).startswith("gen:"):
            raise RequestRefused(
                "invalid-request",
                f"the service ingests generator specs (gen:...); got "
                f"{self.matrix!r}")
        self.dtype = str(doc.get("dtype", cfg.dtype))
        if self.dtype not in ("f32", "f64"):
            raise RequestRefused("invalid-request",
                                 f"dtype must be f32|f64, got "
                                 f"{self.dtype!r}")
        self.algorithm = doc.get("algorithm")
        if self.algorithm is not None:
            from acg_tpu.recurrence import parse_algorithm
            try:
                parse_algorithm(str(self.algorithm))
            except ValueError as e:
                raise RequestRefused("invalid-request", str(e))
        self.precond = doc.get("precond")
        try:
            self.rtol = float(doc.get("rtol", 1e-8))
            self.atol = float(doc.get("atol", 0.0))
            self.maxits = int(doc.get("maxits", 500))
            self.timeout = float(doc.get("timeout",
                                         cfg.default_timeout))
        except (TypeError, ValueError) as e:
            raise RequestRefused("invalid-request",
                                 f"bad numeric field: {e}")
        if self.maxits < 1 or self.timeout <= 0:
            raise RequestRefused("invalid-request",
                                 "maxits must be >= 1 and timeout > 0")
        self.b = doc.get("b")
        self.b_seed = doc.get("b_seed")
        if self.b is not None:
            try:
                self.b = np.asarray(self.b, dtype=np.float64).reshape(-1)
            except (TypeError, ValueError) as e:
                raise RequestRefused("invalid-request", f"bad 'b': {e}")
        self.coalesce = bool(doc.get("coalesce", True))
        self.fault = doc.get("fault")
        if self.fault is not None and not cfg.allow_faults:
            raise RequestRefused(
                "faults-disabled",
                "per-request fault injection is only honoured when the "
                "daemon was started with --serve-faults (the chaos "
                "campaign's hook)", status=403)
        self.want_x = bool(doc.get("return_x", True))
        self.enqueued = time.monotonic()
        self.deadline = self.enqueued + self.timeout
        self.event = threading.Event()
        self.status: int | None = None
        self.response: dict | None = None

    def expired(self) -> bool:
        return time.monotonic() > self.deadline

    def operator_key(self, cfg: ServeConfig) -> tuple:
        return (str(self.matrix), self.dtype, int(cfg.nparts))

    def program_key(self, cfg: ServeConfig, nrhs: int) -> tuple:
        return self.operator_key(cfg) + (
            str(self.algorithm or "classic"),
            str(self.precond or "none"), int(nrhs))

    def coalesce_key(self, cfg: ServeConfig):
        """Requests sharing this key may merge into one batched solve
        and stay BITWISE equal to single service: the batched-classic
        recurrence is column-wise identical only to the classic,
        unpreconditioned single-RHS program (tests/test_batched.py),
        and the shared scalar tolerances must match."""
        if (not self.coalesce or self.fault is not None
                or self.precond is not None
                or self.algorithm not in (None, "classic")):
            return None
        return (str(self.matrix), self.dtype, self.rtol, self.atol,
                self.maxits)

    def finish(self, status: int, body: dict) -> None:
        self.status = int(status)
        self.response = body
        # the respond stage starts here: the submit waiter measures
        # its wakeup against this stamp
        self._finished_at = time.monotonic()
        self.event.set()


def _error_body(kind: str, message: str, req: "_Request | None" = None,
                retryable: bool = False,
                request_id: str | None = None) -> dict:
    body = {"schema": SCHEMA, "ok": False,
            "error": {"type": kind, "message": message,
                      "retryable": bool(retryable)}}
    if req is not None:
        body["id"] = req.id
    rid = (getattr(req, "request_id", None) if req is not None
           else None) or request_id
    if rid:
        body["request_id"] = rid
    return body


# -- bounded request queue -------------------------------------------------

class _Queue:
    """Bounded FIFO with coalesce-aware draining (a plain queue.Queue
    cannot pull compatible followers without popping strangers)."""

    def __init__(self, depth: int):
        self.depth = int(depth)
        self._dq: collections.deque = collections.deque()
        self._cv = threading.Condition()

    def __len__(self):
        with self._cv:
            return len(self._dq)

    def put(self, req: _Request) -> bool:
        from acg_tpu import metrics
        with self._cv:
            if len(self._dq) >= self.depth:
                return False
            self._dq.append(req)
            metrics.record_serve_queue_depth(len(self._dq))
            self._cv.notify()
            return True

    def pop(self, timeout: float):
        from acg_tpu import metrics
        with self._cv:
            if not self._dq:
                self._cv.wait(timeout)
            if not self._dq:
                return None
            req = self._dq.popleft()
            metrics.record_serve_queue_depth(len(self._dq))
            return req

    def drain_compatible(self, key, limit: int) -> list:
        """Remove (in order) up to ``limit`` queued requests whose
        coalesce key equals ``key``."""
        from acg_tpu import metrics
        out = []
        if key is None or limit <= 0:
            return out
        with self._cv:
            keep = collections.deque()
            for r in self._dq:
                if len(out) < limit and r._ckey == key:
                    out.append(r)
                else:
                    keep.append(r)
            self._dq = keep
            metrics.record_serve_queue_depth(len(self._dq))
        return out

    def drain_all(self) -> list:
        with self._cv:
            out = list(self._dq)
            self._dq.clear()
        return out


# -- LRU caches ------------------------------------------------------------

class _LruCache:
    def __init__(self, name: str, size: int):
        self.name = name
        self.size = max(int(size), 1)
        self._d: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.Lock()

    def get(self, key):
        from acg_tpu import metrics
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                metrics.record_serve_cache("hit", self.name)
                return self._d[key]
        metrics.record_serve_cache("miss", self.name)
        return None

    def put(self, key, value) -> list:
        """Insert; returns the evicted ``(key, value)`` pairs."""
        from acg_tpu import metrics
        evicted = []
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.size:
                evicted.append(self._d.popitem(last=False))
                metrics.record_serve_cache("evict", self.name)
        return evicted

    def peek(self, key):
        """Side-effect-free read: no LRU bump, no hit/miss counting
        (the /status path must observe the cache, not perturb it)."""
        with self._lock:
            return self._d.get(key)

    def invalidate(self, key) -> bool:
        from acg_tpu import metrics
        with self._lock:
            hit = self._d.pop(key, None) is not None
        if hit:
            metrics.record_serve_cache("invalidate", self.name)
        return hit

    def invalidate_where(self, pred) -> int:
        from acg_tpu import metrics
        n = 0
        with self._lock:
            for k in [k for k in self._d if pred(k)]:
                del self._d[k]
                n += 1
        for _ in range(n):
            metrics.record_serve_cache("invalidate", self.name)
        return n

    def keys(self) -> list:
        with self._lock:
            return list(self._d.keys())

    def __len__(self):
        with self._lock:
            return len(self._d)


# -- the daemon ------------------------------------------------------------

class ServeDaemon:
    """The long-lived solver service.  Construct, :meth:`start` (binds
    the port, launches the worker), submit requests over HTTP or
    in-process via :meth:`submit`, :meth:`stop` to wind down."""

    def __init__(self, config: ServeConfig):
        self.cfg = config
        self.queue = _Queue(config.queue_depth)
        self.operators = _LruCache("operator",
                                   config.operator_cache_size)
        self.programs = _LruCache("program", config.program_cache_size)
        self.requests_served = 0
        self.requests_failed = 0
        self.warm_restored = 0
        self.started_at = time.time()
        self.accepting = False
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        self._server = None
        self.port: int | None = None
        self._state_lock = threading.Lock()
        # decision observatory: the last planned solve's predicted /
        # measured ratio (surfaced in /status)
        self.last_misprediction: float | None = None
        # request observatory: per-request records, the completed ring
        # (GET /requests) and the acg-tpu-access/1 ledger
        self.reqlog = reqtrace.RequestLog(config.access_log,
                                          ring=config.request_ring)
        # batch ids link coalesced members to their shared solve span
        # (single worker thread owns the counter)
        self._batch_seq = 0

    # -- state persistence (the self-healing warm restore) ----------------

    def _save_state(self) -> None:
        path = self.cfg.state_path
        if not path:
            return
        doc = {"schema": STATE_SCHEMA,
               "operators": [list(k) for k in self.operators.keys()],
               "requests_served": int(self.requests_served),
               "port": self.port, "pid": os.getpid(),
               "unix_time": time.time()}
        tmp = (f"{path}.tmp.{os.getpid()}"
               f".{threading.get_ident()}")
        try:
            # serialized: the worker (batch end) and the main thread
            # (start/stop) both persist -- concurrent writers would
            # steal each other's tmp file out from under os.replace
            with self._state_lock:
                with open(tmp, "w") as f:
                    json.dump(doc, f)
                    f.write("\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
        except OSError as e:
            sys.stderr.write(f"acg-tpu: serve: state write failed: "
                             f"{e}\n")

    def _warm_restore(self) -> None:
        """Re-ingest the operator-cache keys the previous incarnation
        served -- the relaunch pays the ingest ONCE at boot instead of
        on the first unlucky request after every crash."""
        from acg_tpu import metrics, observatory
        path = self.cfg.state_path
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                doc = json.load(f)
            keys = [tuple(k) for k in doc.get("operators", [])]
        except (OSError, ValueError, TypeError) as e:
            sys.stderr.write(f"acg-tpu: serve: unreadable state "
                             f"{path}: {e} (cold start)\n")
            return
        n = 0
        for key in keys:
            try:
                matrix, dtype = str(key[0]), str(key[1])
                self._ingest_operator((matrix, dtype,
                                       int(self.cfg.nparts)))
                n += 1
            except Exception as e:  # noqa: BLE001 -- a stale key must
                sys.stderr.write(   # not kill the restore
                    f"acg-tpu: serve: warm restore of {key} failed: "
                    f"{e}\n")
        if n:
            self.warm_restored = n
            metrics.record_serve_warm_restore(n)
            observatory.note_event(
                "serve-warm-restore",
                f"re-ingested {n} operator(s) from {path}")
            sys.stderr.write(f"acg-tpu: serve: warm-restored {n} "
                             f"operator(s) from {path}\n")

    # -- operator / program construction -----------------------------------

    def _jnp_dtype(self, dtype: str):
        import jax.numpy as jnp
        return jnp.float64 if dtype == "f64" else jnp.float32

    def _ingest_operator(self, key: tuple) -> tuple:
        """Build (and cache) the ingested operator for ``key`` =
        (matrix, dtype, nparts); returns ``(entry, was_hit)``.
        Counts hit/miss on the operator cache."""
        entry = self.operators.get(key)
        if entry is not None:
            return entry, True
        matrix, dtype, nparts = key
        from acg_tpu.cli import synthesize_host_matrix
        t0 = time.perf_counter()
        sym = synthesize_host_matrix(matrix)
        csr = sym.to_csr()
        dt = self._jnp_dtype(dtype)
        entry = {"csr": csr, "dtype": dtype, "n": int(csr.shape[0])}
        if int(nparts) > 1:
            from acg_tpu.parallel.dist import DistributedProblem
            from acg_tpu.partition import partition_rows
            part = partition_rows(csr, int(nparts), seed=0,
                                  method="band")
            entry["prob"] = DistributedProblem.build(
                csr, part, int(nparts), dtype=dt)
        else:
            from acg_tpu.ops.spmv import device_matrix_from_csr
            entry["A"] = device_matrix_from_csr(csr, dtype=dt)
        entry["ingest_seconds"] = time.perf_counter() - t0
        if self.cfg.autotune:
            # decision observatory: plan on operator-cache miss -- the
            # decision is cached alongside the operator (and the
            # compiled programs it selects), replanned when the
            # calibration id changes (_solve_batch)
            entry["plan"] = self._plan_operator(key, entry)
        for (ekey, _val) in self.operators.put(key, entry):
            # dependent compiled programs hold the evicted operator's
            # device buffers alive -- drop them with it
            self.programs.invalidate_where(lambda k: k[:3] == ekey)
        return entry, False

    def _calibration_id(self) -> str:
        from acg_tpu.commbench import UNCALIBRATED, calibration_id
        cal = self.cfg.calibration
        if not isinstance(cal, dict):
            return UNCALIBRATED
        return cal.get("calibration_id") or calibration_id(cal)

    def set_calibration(self, cal: dict | None) -> None:
        """Swap the live calibration document.  Cached decisions keep
        their recorded calibration id, so the next planned request for
        each operator notices the mismatch and replans."""
        self.cfg.calibration = cal

    def _plan_operator(self, key: tuple, entry: dict) -> dict | None:
        """One planning pass for a freshly ingested operator: rank the
        candidate space the daemon can actually dispatch (its fixed
        kernels/transport; the recurrence is the free axis) and return
        the decision.  Planning failing is never fatal -- the request
        falls back to the flag-selected program."""
        from acg_tpu import observatory, planner
        matrix, dtype, nparts = key
        try:
            import jax
            itemsize = 8 if dtype == "f64" else 4
            kappa, src = planner.kappa_estimate(entry["csr"], 1e-8, 500)
            bw, disp = planner._probe_constants(
                self._jnp_dtype(dtype), jax.default_backend() == "tpu")
            doc = planner.build_plan(
                entry["csr"], matrix_id=str(matrix),
                nparts=max(int(nparts), 1), dtype_name=str(dtype),
                rtol=1e-8, maxits=500, mat_itemsize=itemsize,
                vec_itemsize=itemsize, cal=self.cfg.calibration,
                kappa=kappa, kappa_source=src, bw_gbs=bw,
                dispatch_s=disp, backend=jax.default_backend(),
                kernels=("auto",), comms=(self.cfg.comm,))
            if not doc["ranked"]:
                return None
            top = doc["ranked"][0]
            decision = {
                "plan_id": doc["plan_id"],
                "calibration": doc["calibration"],
                "selected": top["label"],
                "algorithm": top["algorithm"],
                "predicted_s_per_solve": top["predicted_s_per_solve"],
                "predicted_iterations": top["predicted_iterations"],
            }
            observatory.note_event(
                "serve-planned",
                f"operator {matrix}: {top['label']} (plan "
                f"{doc['plan_id']}, calibration {doc['calibration']})")
            return decision
        except Exception as e:  # noqa: BLE001 -- planning is advisory
            sys.stderr.write(f"acg-tpu: serve: planning {matrix} "
                             f"failed: {type(e).__name__}: {e}\n")
            return None

    def _build_solver(self, req: _Request, op: dict, nrhs: int):
        from acg_tpu.solvers.resilience import RecoveryPolicy
        pol = RecoveryPolicy(max_restarts=2,
                             backoff=self.cfg.retry_backoff)
        algorithm = req.algorithm
        if "prob" in op:
            if nrhs > 1:
                from acg_tpu.parallel.dist_batched import \
                    BatchedDistCGSolver
                return BatchedDistCGSolver(op["prob"])
            from acg_tpu.parallel.dist import DistCGSolver
            return DistCGSolver(op["prob"], comm=self.cfg.comm,
                                precond=req.precond, recovery=pol,
                                algorithm=algorithm)
        if nrhs > 1:
            from acg_tpu.solvers.batched import BatchedCGSolver
            return BatchedCGSolver(op["A"], mode="batched",
                                   host_matrix=op["csr"])
        from acg_tpu.solvers.jax_cg import JaxCGSolver
        # kernels="xla" keeps the single-RHS program column-identical
        # to the batched tier's (the coalescing bitwise guarantee)
        return JaxCGSolver(op["A"], kernels="xla",
                           precond=req.precond, recovery=pol,
                           host_matrix=op["csr"],
                           algorithm=algorithm)

    def _program_for(self, req: _Request, op: dict, nrhs: int):
        """(solver, was_hit) for this request shape."""
        key = req.program_key(self.cfg, nrhs)
        solver = self.programs.get(key)
        if solver is not None:
            return solver, True
        solver = self._build_solver(req, op, nrhs)
        self.programs.put(key, solver)
        return solver, False

    # -- admission ---------------------------------------------------------

    def _burn(self) -> float:
        from acg_tpu import observatory
        rep = observatory.slo_report()
        burns = list((rep.get("burn") or {}).values())
        return max(burns) if burns else 0.0

    def admit(self, req: _Request) -> None:
        """Admission control; raises :class:`RequestRefused` with the
        typed shed reason instead of queueing."""
        from acg_tpu import metrics
        if not self.accepting:
            metrics.record_serve_shed("shutdown")
            raise RequestRefused(
                "shed-shutdown", "the service is shutting down",
                status=503)
        burn = self._burn()
        req._admit_burn = burn
        if burn >= self.cfg.shed_burn:
            metrics.record_serve_shed("slo-burn")
            raise RequestRefused(
                "shed-slo-burn",
                f"SLO error-budget burn {burn:.2f} is past the shed "
                f"threshold {self.cfg.shed_burn:.2f}; retry later",
                status=503)
        req._ckey = req.coalesce_key(self.cfg)
        if not self.queue.put(req):
            metrics.record_serve_shed("queue-full")
            raise RequestRefused(
                "shed-queue-full",
                f"request queue is full (depth "
                f"{self.cfg.queue_depth}); retry later", status=429)

    def submit(self, doc: dict) -> tuple:
        """The in-process request path (the HTTP handler's core, also
        the test hook): validate, admit, wait for the worker, return
        ``(http_status, response_dict)`` -- ALWAYS within the
        request's deadline plus a small grace.  Every path through
        here -- green, shed, invalid, expired -- opens and seals one
        request-observatory record, so the access ledger carries
        exactly one row per request."""
        from acg_tpu import metrics
        rid = reqtrace.request_id_from_doc(doc)
        rec = self.reqlog.begin(
            rid, matrix=((doc.get("matrix") if isinstance(doc, dict)
                          else None) or self.cfg.preload))
        t_admit0 = time.monotonic()
        try:
            req = _Request(doc, self.cfg)
        except RequestRefused as e:
            metrics.record_serve_request("invalid")
            rec.stage("admit", time.monotonic() - t_admit0,
                      decision=e.kind)
            self.reqlog.complete(rec, "invalid-request")
            return e.status, _error_body(e.kind, str(e),
                                         request_id=rid)
        req.request_id = rid
        req.trace = rec
        rec.id = req.id
        rec.matrix = str(req.matrix)
        try:
            self.admit(req)
        except RequestRefused as e:
            metrics.record_serve_request("shed")
            rec.stage("admit", time.monotonic() - t_admit0,
                      burn=getattr(req, "_admit_burn", None),
                      decision=e.kind)
            self.reqlog.complete(
                rec, e.kind if e.kind.startswith("shed-")
                else "request-failed")
            return e.status, _error_body(e.kind, str(e), req,
                                         retryable=True)
        rec.stage("admit", time.monotonic() - t_admit0,
                  burn=getattr(req, "_admit_burn", None),
                  decision="admitted")
        if not req.event.wait(req.timeout + 1.0):
            metrics.record_serve_shed("deadline")
            metrics.record_serve_request("expired")
            self.reqlog.complete(rec, "deadline-expired")
            return 504, _error_body(
                "deadline-expired",
                f"request {req.id} was not answered within its "
                f"{req.timeout:g}s deadline", req, retryable=True)
        t_fin = getattr(req, "_finished_at", None)
        if t_fin is not None:
            rec.stage("respond", time.monotonic() - t_fin)
        self.reqlog.complete(rec, reqtrace.outcome_of(req.response))
        return req.status, req.response

    # -- the worker --------------------------------------------------------

    def _degraded(self, req: _Request) -> bool:
        """The degrade rung of the shed ladder: past ``degrade_burn``
        the request is served on the cheap profile (classic
        recurrence, no preconditioner) instead of refused."""
        if self._burn() < self.cfg.degrade_burn:
            return False
        return (req.algorithm not in (None, "classic")
                or req.precond is not None)

    def _request_b(self, req: _Request, n: int) -> np.ndarray:
        if req.b is not None:
            if req.b.size != n:
                raise RequestRefused(
                    "invalid-request",
                    f"'b' has {req.b.size} entries; {req.matrix} has "
                    f"{n} rows")
            return req.b
        if req.b_seed is not None:
            return np.random.default_rng(
                int(req.b_seed)).standard_normal(n)
        return np.ones(n)

    def _serve_fault(self, req: _Request) -> None:
        """Host-level fault sites for the chaos campaign: ``crash``
        kills the daemon mid-request (the supervisor's relaunch
        trigger); ``slow:S`` dilates service (the SLO-burn trigger).
        Device-site specs are injected around the solve instead."""
        f = str(req.fault or "")
        if f.startswith("crash"):
            sys.stderr.write(f"acg-tpu: serve: request {req.id} "
                             f"injected crash -- daemon exiting\n")
            sys.stderr.flush()
            os._exit(int(ExitCode.CRASH_INJECTED))
        if f.startswith("slow:"):
            time.sleep(float(f.split(":", 1)[1]))

    def _solve_batch(self, batch: list) -> None:
        """Serve one coalesced batch (len >= 1) end to end: cache
        lookups, the solve, demux, per-request responses.  All
        failure paths answer every member with a TYPED error."""
        from acg_tpu import faults, metrics, observatory, tracing
        from acg_tpu.solvers import StoppingCriteria
        lead = batch[0]
        nrhs = len(batch)
        degraded = False
        self._batch_seq += 1
        bid = self._batch_seq
        member_ids = [getattr(r, "request_id", None) for r in batch]
        try:
            if lead.fault:
                self._serve_fault(lead)
            degraded = self._degraded(lead)
            if degraded:
                lead.algorithm = None
                lead.precond = None
                metrics.record_serve_degraded()
                observatory.note_event(
                    "serve-degraded",
                    f"request {lead.id} [{lead.request_id}] "
                    f"downgraded to the classic "
                    f"unpreconditioned profile (SLO burn "
                    f"{self._burn():.2f})")
            t_cache0 = time.perf_counter()
            op, op_hit = self._ingest_operator(
                lead.operator_key(self.cfg))
            ingest_dt = time.perf_counter() - t_cache0
            # decision observatory: resolve this batch's program
            # provenance.  degraded beats everything (the shed ladder
            # already stripped algorithm/precond); an explicit request
            # field is flag-forced; otherwise the cached plan decides
            # -- replanned first when the calibration id changed
            decision = op.get("plan") if self.cfg.autotune else None
            if self.cfg.autotune and decision is not None:
                cal_now = self._calibration_id()
                if decision.get("calibration") != cal_now:
                    observatory.note_event(
                        "serve-replanned",
                        f"operator {lead.matrix}: calibration "
                        f"{decision.get('calibration')} -> {cal_now}")
                    decision = self._plan_operator(
                        lead.operator_key(self.cfg), op)
                    op["plan"] = decision
            if degraded:
                plan_source = "fallback"
            elif lead.algorithm is not None or lead.precond is not None:
                plan_source = "flag-forced"
            elif decision is not None:
                plan_source = "planned"
                # the planned recurrence only applies to single-RHS
                # service: coalesced batches ride the batched-classic
                # program (the bitwise coalescing contract)
                if nrhs == 1 \
                        and decision.get("algorithm") != "classic":
                    lead.algorithm = decision["algorithm"]
            else:
                plan_source = "flag-forced"
            plan_body = {"id": (decision or {}).get("plan_id"),
                         "source": plan_source}
            n = op["n"]
            cols = [self._request_b(r, n) for r in batch]
            b = cols[0] if nrhs == 1 else np.stack(cols, axis=1)
            crit = StoppingCriteria(maxits=lead.maxits,
                                    residual_rtol=lead.rtol,
                                    residual_atol=lead.atol)
            t0 = time.perf_counter()
            x, solver, prog_hit, prog_dt, ninval = \
                self._solve_with_retries(lead, op, nrhs, b, crit)
            latency = time.perf_counter() - t0
            st = solver.stats
            observatory.slo_observe(st, latency=latency,
                                    iterations=int(st.niterations))
            if nrhs > 1:
                metrics.record_serve_coalesced(nrhs)
            if plan_source == "planned" and latency > 0 \
                    and decision.get("predicted_s_per_solve"):
                ratio = float(decision["predicted_s_per_solve"]) \
                    / latency
                self.last_misprediction = ratio
                metrics.record_plan_misprediction(ratio)
            # tail-latency attribution: the program build (billed to
            # the cache stage with the operator ingest) and the compile
            # a cache-miss solve absorbed in warmup are carved out of
            # the measured latency; what remains is PURE solve, split
            # per RHS so member attributions sum to the batch solve
            # time -- and stage sums never exceed the request wall
            compile_s = min(max(float((st.timings or {}).get(
                "compile", 0.0) or 0.0), 0.0), latency)
            solve_s = max(latency - compile_s
                          - min(max(prog_dt, 0.0), latency), 0.0)
            rhs_share = solve_s / nrhs
            # ONE batch-scoped solve span linked to every member id --
            # the coalesced batch's row on the service timeline
            t_wall = time.time()
            tracing.record_span(
                f"solve-batch-{bid}", t_wall - latency, t_wall,
                cat="worker", batch=bid, nrhs=nrhs,
                requests=[m for m in member_ids if m])
            prog_state = ("invalidated" if ninval
                          else ("hit" if prog_hit else "miss"))
            cache_body = {"operator": "hit" if op_hit else "miss",
                          "program": "hit" if prog_hit else "miss"}
            batch_block = {"id": bid, "width": nrhs,
                           "members": [m for m in member_ids if m],
                           "solve_seconds": round(solve_s, 6),
                           "rhs_solve_seconds": round(rhs_share, 6)}
            X = np.asarray(x)
            for j, r in enumerate(batch):
                t_demux0 = time.perf_counter()
                xj = X[:, j] if nrhs > 1 else X
                iters = (int(st.batch["iterations"][j])
                         if nrhs > 1 and st.batch else
                         int(st.niterations))
                body = {"schema": SCHEMA, "ok": True, "id": r.id,
                        "request_id": r.request_id,
                        "converged": bool(st.converged),
                        "iterations": iters,
                        "latency_seconds": round(latency, 6),
                        "coalesced": nrhs, "degraded": degraded,
                        "plan": dict(plan_body),
                        "cache": dict(cache_body)}
                if r.want_x:
                    body["x"] = xj.tolist()
                rec = getattr(r, "trace", None)
                if rec is not None:
                    rec.stage("cache", ingest_dt + prog_dt,
                              operator=cache_body["operator"],
                              program=prog_state)
                    if compile_s > 0:
                        rec.stage("compile", compile_s)
                    rec.stage("solve", rhs_share, batch=bid)
                    rec.note("cache", {"operator":
                                       cache_body["operator"],
                                       "program": prog_state})
                    rec.note("coalesced", nrhs)
                    rec.note("degraded", bool(degraded))
                    rec.note("plan", dict(plan_body))
                    rec.note("batch", dict(batch_block))
                    rec.stage("demux",
                              time.perf_counter() - t_demux0)
                r.finish(200, body)
                metrics.record_plan_decision(plan_source)
                metrics.record_serve_request("ok")
                self.requests_served += 1
            self._save_state()
        except RequestRefused as e:
            for r in batch:
                r.finish(e.status, _error_body(e.kind, str(e), r))
                metrics.record_serve_request("invalid")
        except Exception as e:  # noqa: BLE001 -- the isolation
            # boundary: ANY request failure becomes a typed answer
            kind = type(e).__name__
            observatory.note_event(
                "request-failed",
                f"request {lead.id} [{lead.request_id}] "
                f"({lead.matrix}): {kind}: {e}")
            sys.stderr.write(f"acg-tpu: serve: request {lead.id} "
                             f"[{lead.request_id}] failed: "
                             f"{kind}: {e}\n")
            for r in batch:
                r.finish(500, _error_body(
                    kind, str(e), r,
                    retryable=isinstance(e, (BreakdownError,
                                             NotConvergedError))))
                metrics.record_serve_request("error")
                self.requests_failed += 1
        finally:
            _ = faults  # keep the import local-and-single

    def _solve_with_retries(self, lead: _Request, op: dict, nrhs: int,
                            b, crit):
        """The bounded per-request retry loop around the solve.  A
        breakdown that escapes the solver's own recovery ladder
        invalidates the (possibly poisoned) program-cache entry,
        backs off, and retries with a freshly built program; the
        LAST failure propagates to the typed-error boundary.
        Returns ``(x, solver, prog_hit, program_lookup_seconds,
        ninvalidated)`` -- the lookup time feeds the cache stage, the
        invalidation count the ledger's program provenance."""
        from acg_tpu import faults, observatory
        attempt = 0
        prog_dt = 0.0
        ninval = 0
        while True:
            op_entry = op
            t_p0 = time.perf_counter()
            solver, prog_hit = self._program_for(lead, op_entry, nrhs)
            prog_dt += time.perf_counter() - t_p0
            # a cache-miss solve absorbs (and counts) its compile in
            # warmup; a cache-hit solve must NOT pay or count one
            warmup = 0 if prog_hit else 1
            try:
                f = lead.fault
                if f and not (f.startswith("crash")
                              or f.startswith("slow:")):
                    with faults.injected(str(f)):
                        x = solver.solve(b, criteria=crit,
                                         warmup=warmup)
                else:
                    x = solver.solve(b, criteria=crit, warmup=warmup)
                return x, solver, prog_hit, prog_dt, ninval
            except NotConvergedError:
                # ran to maxits healthy -- a retry re-runs the same
                # trajectory; answer typed instead
                raise
            except (BreakdownError, FloatingPointError,
                    AcgError) as e:
                self.programs.invalidate(
                    lead.program_key(self.cfg, nrhs))
                ninval += 1
                # a poisoned request traces END TO END: the
                # invalidation event and the retry line both carry
                # the stable request identity
                observatory.note_event(
                    "serve-program-invalidated",
                    f"request {lead.id} [{lead.request_id}]: program "
                    f"cache entry for {lead.matrix} invalidated "
                    f"after {type(e).__name__}")
                if attempt >= self.cfg.retries:
                    raise
                attempt += 1
                sys.stderr.write(
                    f"acg-tpu: serve: request {lead.id} "
                    f"[{lead.request_id}] retry "
                    f"{attempt}/{self.cfg.retries} after "
                    f"{type(e).__name__}\n")
                time.sleep(self.cfg.retry_backoff * (2 ** (attempt - 1)))
                # the fault modelled a transient -- drop it on retry
                lead.fault = None

    def _worker_loop(self) -> None:
        from acg_tpu import metrics
        while not self._stop.is_set():
            req = self.queue.pop(timeout=0.1)
            if req is None:
                continue
            t_pop = time.monotonic()
            rec = getattr(req, "trace", None)
            if req.expired():
                metrics.record_serve_shed("deadline")
                metrics.record_serve_request("expired")
                if rec is not None:
                    rec.stage("queue-wait", t_pop - req.enqueued)
                req.finish(504, _error_body(
                    "deadline-expired",
                    f"request {req.id} expired in queue", req,
                    retryable=True))
                continue
            batch = [req]
            key = getattr(req, "_ckey", None)
            if key is not None and self.cfg.coalesce > 1:
                deadline = time.monotonic() + COALESCE_WINDOW_SECS
                while (len(batch) < self.cfg.coalesce
                       and time.monotonic() < deadline):
                    more = self.queue.drain_compatible(
                        key, self.cfg.coalesce - len(batch))
                    if more:
                        batch.extend(more)
                    else:
                        time.sleep(0.005)
            # per-request attribution: the lead paid queue-wait until
            # its pop and the coalesce window after it; a follower's
            # whole wait (including the window that scooped it up) is
            # queue residency
            t_batch = time.monotonic()
            if rec is not None:
                rec.stage("queue-wait", t_pop - req.enqueued)
                rec.stage("coalesce", t_batch - t_pop,
                          followers=len(batch) - 1)
            for r in batch[1:]:
                fr = getattr(r, "trace", None)
                if fr is not None:
                    fr.stage("queue-wait", t_batch - r.enqueued)
            self._solve_batch(batch)
        # shutdown: answer the stragglers, never strand a waiter
        for r in self.queue.drain_all():
            from acg_tpu import metrics
            metrics.record_serve_shed("shutdown")
            metrics.record_serve_request("shed")
            r.finish(503, _error_body(
                "shed-shutdown", "the service is shutting down", r,
                retryable=True))

    # -- status ------------------------------------------------------------

    def status_doc(self) -> dict:
        from acg_tpu import observatory
        doc = {"schema": SCHEMA, "serving": self.accepting,
               "pid": os.getpid(), "port": self.port,
               "uptime_seconds": round(time.time() - self.started_at,
                                       3),
               "queue_depth": len(self.queue),
               "queue_limit": self.cfg.queue_depth,
               "requests_served": self.requests_served,
               "requests_failed": self.requests_failed,
               "warm_restored": self.warm_restored,
               "operator_cache": {"entries": len(self.operators),
                                  "keys": [list(k) for k in
                                           self.operators.keys()]},
               "program_cache": {"entries": len(self.programs)},
               "slo_burn": round(self._burn(), 4),
               "nparts": self.cfg.nparts}
        # request observatory: in-flight / completed tallies and the
        # outcome histogram (GET /requests serves the documents)
        doc["requests"] = self.reqlog.summary()
        # decision observatory: what the daemon would dispatch and how
        # honest the last planned prediction was
        cached = []
        for key in self.operators.keys():
            entry = self.operators.peek(key)
            dec = (entry or {}).get("plan")
            if dec:
                cached.append({"matrix": key[0],
                               "plan_id": dec.get("plan_id"),
                               "selected": dec.get("selected"),
                               "calibration": dec.get("calibration")})
        doc["plans"] = {
            "autotune": bool(self.cfg.autotune),
            "calibration": self._calibration_id(),
            "decisions": cached,
            "last_misprediction_ratio": self.last_misprediction,
        }
        doc["status"] = observatory.status_document()
        return doc

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        """Arm the planes, warm-restore, bind the port, go.  Returns
        the bound port (``cfg.port == 0`` lets the OS pick -- the
        test hook, the ``--metrics-port`` design)."""
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        from acg_tpu import metrics, observatory
        metrics.arm()
        observatory.arm()
        self._warm_restore()
        if self.cfg.preload:
            self._ingest_operator((str(self.cfg.preload),
                                   self.cfg.dtype,
                                   int(self.cfg.nparts)))
        self.accepting = True
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="acg-serve-worker",
                                        daemon=True)
        self._worker.start()
        daemon = self

        class _Handler(BaseHTTPRequestHandler):
            def _reply(self, status: int, body: dict,
                       ctype: str = "application/json") -> None:
                data = (json.dumps(body) + "\n").encode()
                self.send_response(int(status))
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 -- stdlib handler contract
                path = self.path.split("?")[0]
                if path in ("/status", "/"):
                    self._reply(200, daemon.status_doc())
                elif path == "/requests":
                    self._reply(200, daemon.reqlog.snapshot())
                elif path == "/healthz":
                    self._reply(200 if daemon.accepting else 503,
                                {"ok": daemon.accepting})
                elif path == "/metrics":
                    body = metrics.expose().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length",
                                     str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802 -- stdlib handler contract
                path = self.path.split("?")[0]
                if path == "/shutdown":
                    self._reply(200, {"ok": True,
                                      "detail": "shutting down"})
                    threading.Thread(target=daemon.stop,
                                     daemon=True).start()
                    return
                if path != "/solve":
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length",
                                                  0))
                    doc = json.loads(
                        self.rfile.read(length).decode() or "{}")
                    if not isinstance(doc, dict):
                        raise ValueError("request body must be a "
                                         "JSON object")
                except (ValueError, UnicodeDecodeError) as e:
                    metrics.record_serve_request("invalid")
                    self._reply(400, _error_body("invalid-request",
                                                 f"bad JSON: {e}"))
                    return
                status, body = daemon.submit(doc)
                self._reply(status, body)

            def log_message(self, *a):  # clients must not spam stderr
                pass

        self._server = ThreadingHTTPServer(("", self.cfg.port),
                                           _Handler)
        self.port = int(self._server.server_address[1])
        threading.Thread(target=self._server.serve_forever,
                         name="acg-serve-http", daemon=True).start()
        self._save_state()
        observatory.note_event("serve-start",
                               f"solver service on port {self.port} "
                               f"(pid {os.getpid()})")
        sys.stderr.write(f"acg-tpu: serve: listening on port "
                         f"{self.port} (pid {os.getpid()})\n")
        return self.port

    def stop(self) -> None:
        self.accepting = False
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=10.0)
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        self._save_state()
        self.reqlog.close()


# -- CLI entry -------------------------------------------------------------

def _serve_validate(args) -> None:
    """The could-never-fire discipline for ``--serve``: refuse every
    one-shot flag the daemon could never honour, BEFORE binding a
    port."""
    unsupported = [flag for flag, on in [
        ("--soak (the daemon IS the service loop)",
         bool(getattr(args, "soak", 0))),
        ("--resume (the daemon warm-restores from its own serve "
         "state)", args.resume is not None),
        ("b/x0 input files (each request carries its own b)",
         bool(args.b or args.x0)),
        ("-o/--output (solutions ride the HTTP responses)",
         getattr(args, "output", None) is not None),
        ("--explain", bool(getattr(args, "explain", False))),
        ("--bench", bool(getattr(args, "bench", False))),
        ("--nrhs/--block-cg (the coalescer owns batching)",
         int(getattr(args, "nrhs", 0) or 0) >= 2
         or bool(getattr(args, "block_cg", False))),
        ("--fault-inject (requests carry their own fault field "
         "under --serve-faults)",
         getattr(args, "fault_inject", None) is not None),
        ("--manufactured-solution",
         bool(getattr(args, "manufactured_solution", False))),
        ("--distributed-read",
         bool(getattr(args, "distributed_read", False))),
        ("--output-comm-matrix",
         bool(getattr(args, "output_comm_matrix", False))),
        ("--profile-ops",
         getattr(args, "profile_ops", None) is not None),
        ("--plan (the daemon plans per operator; GET /status shows "
         "the cached decisions)",
         getattr(args, "plan", None) is not None),
    ] if on]
    if unsupported:
        raise SystemExit(f"acg-tpu: --serve does not support: "
                         f"{', '.join(unsupported)}")
    if not str(args.A).startswith("gen:"):
        raise SystemExit(
            "acg-tpu: --serve preloads a generator operator "
            "(gen:...); file matrices are not served yet")


def config_from_args(args) -> ServeConfig:
    state = args.ckpt
    if state is not None and not state.endswith(".serve.json"):
        state = state + ".serve.json"
    # --serve dispatches before _main's calibration load; mirror it
    # (the x64 mirroring pattern in run_serve)
    cal = getattr(args, "_calibration", None)
    if cal is None and getattr(args, "calibration", None):
        from acg_tpu.commbench import load_calibration
        try:
            cal = load_calibration(args.calibration)
        except (OSError, ValueError) as e:
            raise SystemExit(f"acg-tpu: --calibration "
                             f"{args.calibration}: {e}")
    return ServeConfig(
        port=int(getattr(args, "serve_port", 0) or 0),
        queue_depth=int(getattr(args, "serve_queue_depth", 16)),
        coalesce=int(getattr(args, "serve_coalesce", 8)),
        default_timeout=float(getattr(args, "serve_deadline", 60.0)),
        state_path=state, preload=str(args.A),
        nparts=int(args.nparts or 0),
        comm="dma" if getattr(args, "comm", "xla") in ("dma",
                                                       "nvshmem")
        else "xla",
        dtype="f64" if args.dtype == "f64" else "f32",
        allow_faults=bool(getattr(args, "serve_faults", False)),
        autotune=bool(getattr(args, "autotune", False)),
        calibration=cal,
        access_log=getattr(args, "access_log", None))


def run_serve(args, argv: list) -> int:
    """The ``--serve`` CLI mode: plain daemon, supervised daemon
    (``--supervise``), or the live chaos campaign (``--chaos``)."""
    _serve_validate(args)
    # --serve dispatches BEFORE _main's per-solve platform setup, so
    # mirror it here: a long-lived daemon must be able to answer f64
    # requests (x64 is a process-global switch that cannot flip after
    # the first trace; f32 requests keep their explicit dtype)
    import jax

    from acg_tpu._platform import enable_compile_cache, \
        honour_jax_platforms
    honour_jax_platforms()
    jax.config.update("jax_enable_x64", True)
    enable_compile_cache()
    if args.chaos is not None:
        return run_chaos_serve(args, argv)
    if args.supervise:
        from acg_tpu.supervisor import run_supervised_serve
        return run_supervised_serve(args, argv)
    from acg_tpu import metrics, observatory
    if args.slo:
        observatory.install_slo(observatory.parse_slo(args.slo))
    # --serve --timeline FILE = the SERVICE timeline: the daemon owns
    # the span recorder for its lifetime (serve dispatches before
    # _main's per-solve arm/export), one worker row plus one lane per
    # in-flight request window, exported at shutdown
    timeline = getattr(args, "timeline", None)
    if timeline:
        from acg_tpu import tracing
        tracing.arm()
    daemon = ServeDaemon(config_from_args(args))
    daemon.start()
    if args.metrics_port:
        metrics.serve(args.metrics_port)
    if args.status_port:
        observatory.serve_status(args.status_port)
    if args.metrics_file:
        metrics.install_flush_handlers(args.metrics_file)
    import signal

    def _term(signum, frame):
        sys.stderr.write("acg-tpu: serve: signal "
                         f"{signum} -- shutting down\n")
        threading.Thread(target=daemon.stop, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _term)
        signal.signal(signal.SIGINT, _term)
    except ValueError:
        pass  # not the main thread (in-process callers)
    try:
        while daemon._server is not None and not daemon._stop.is_set():
            time.sleep(0.2)
    except KeyboardInterrupt:
        daemon.stop()
    sys.stderr.write(f"acg-tpu: serve: served "
                     f"{daemon.requests_served} request(s), "
                     f"{daemon.requests_failed} failed -- bye\n")
    if timeline:
        from acg_tpu import tracing
        try:
            summary = tracing.export_chrome_trace(
                timeline, [tracing.local_payload()],
                nparts=max(int(args.nparts or 0), 1))
            sys.stderr.write(
                f"acg-tpu: --timeline {timeline}: service timeline, "
                f"{summary['nspans']} span(s)\n")
        except OSError as e:
            sys.stderr.write(f"acg-tpu: --timeline {timeline}: "
                             f"{e}\n")
        finally:
            tracing.disarm()
    if args.metrics_file:
        try:
            metrics.write_textfile(args.metrics_file)
        except OSError as e:
            sys.stderr.write(f"acg-tpu: --metrics-file "
                             f"{args.metrics_file}: {e}\n")
    return 0


# -- the live chaos campaign ----------------------------------------------

def _http_json(method: str, url: str, doc=None, timeout: float = 30.0):
    """(status, parsed-body) with stdlib urllib; connection-level
    failures surface as OSError to the caller."""
    import urllib.error
    import urllib.request
    data = None if doc is None else json.dumps(doc).encode()
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode())
        except ValueError:
            return e.code, {"ok": False,
                            "error": {"type": "http",
                                      "message": str(e)}}


def serve_chaos_schedule(index: int, seed: int, nparts: int) -> dict:
    """Schedule ``index``'s request mutation -- deterministic in
    (seed, index) like :func:`acg_tpu.supervisor.chaos_schedule`, over
    the sites a LIVE daemon can exercise.  Schedule 1 is ALWAYS a
    crash-mid-request: every campaign of >= 2 schedules exercises the
    kill-and-relaunch path regardless of seed (the acceptance's
    non-negotiable case), the rest of the menu stays seeded."""
    rng = np.random.default_rng([int(seed), int(index), 77])
    menu = ["none", "none", "crash", "slow", "spmv:nan", "dot:nan"]
    if int(nparts) > 1:
        menu.append("halo:nan")
    pick = "crash" if int(index) == 1 \
        else menu[int(rng.integers(len(menu)))]
    if pick == "none":
        return {}
    if pick == "crash":
        return {"fault": "crash"}
    if pick == "slow":
        return {"fault": f"slow:{0.05 + 0.1 * float(rng.random()):.3f}"}
    k = 2 + int(6 * float(rng.random()) ** 2)
    if pick == "dot:nan":
        return {"fault": f"dot:nan@{k}"}
    return {"fault": f"{pick}@{k}:seed={int(rng.integers(1 << 16))}"}


def run_chaos_serve(args, argv: list) -> int:
    """``--serve --chaos SEED[:N]``: the campaign against the LIVE
    daemon.  A supervised daemon is launched as a child; every
    schedule fires one request (possibly fault-carrying) at it, every
    green response is verified against the host oracle
    independently, and every verdict lands in the ledger.  Exit 96 on
    any wrong-answer-green; the daemon must still be serving at the
    end."""
    from acg_tpu import metrics, observatory
    from acg_tpu.supervisor import (SUPERVISOR_FLAGS, parse_chaos,
                                    set_flag, strip_flags, supervise_daemon,
                                    verify_solution_dense)
    seed, nsched = parse_chaos(args.chaos)
    if args.ckpt is None:
        raise SystemExit(
            "acg-tpu: --serve --chaos relaunches the daemon from its "
            "persisted serve state; arm --ckpt FILE")
    from acg_tpu.cli import synthesize_host_matrix
    csr = synthesize_host_matrix(args.A).to_csr()
    metrics.arm()
    child_argv = strip_flags(argv, SUPERVISOR_FLAGS)
    port = int(getattr(args, "serve_port", 0) or 0)
    if port == 0:
        # the campaign needs a STABLE address across daemon relaunches
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        child_argv = set_flag(child_argv, "--serve-port", port)
    env = dict(os.environ)
    env[FAULTS_ENV] = "1"
    env.pop("ACG_TPU_FAULT_INJECT", None)
    sup = supervise_daemon(
        child_argv, state_path=args.ckpt + ".serve.json",
        budget=max(args.relaunch_budget, nsched), backoff=0.1,
        env=env, label="chaos-serve")
    base = f"http://127.0.0.1:{port}"
    try:
        if not _wait_serving(base, 120.0):
            sup.stop()
            raise SystemExit("acg-tpu: --serve --chaos: the daemon "
                             "never came up")
        tally = {"verified": 0, "typed-error": 0, "crash-relaunched": 0,
                 "WRONG-ANSWER": 0, "HANG": 0}
        sys.stderr.write(f"acg-tpu: chaos-serve: {nsched} schedules "
                         f"from seed {seed} against {base}\n")
        for i in range(nsched):
            sched = serve_chaos_schedule(i, seed, int(args.nparts or 0))
            rng = np.random.default_rng([seed, i, 3])
            doc = {"matrix": args.A, "b_seed": int(rng.integers(1 << 30)),
                   "rtol": float(args.residual_rtol or 1e-8),
                   "maxits": int(args.max_iterations),
                   "timeout": 120.0,
                   "request_id": f"chaos-{seed}-{i}", **sched}
            verdict, rel, rid = _chaos_request(base, doc, csr,
                                               verify_solution_dense)
            if verdict == "crash-relaunched":
                if not _wait_serving(base, 120.0):
                    verdict = "HANG"
            tally[verdict] = tally.get(verdict, 0) + 1
            sys.stderr.write(
                f"acg-tpu: chaos-serve[{i}]: "
                f"fault={sched.get('fault') or 'none'} -> {verdict}"
                f"{f' (rel {rel:.3e})' if rel is not None else ''}\n")
            if args.history:
                try:
                    observatory.history_append(args.history, {
                        "schema": "acg-tpu-chaos-serve/1",
                        "chaos": {"schedule": i, "seed": seed,
                                  "fault": sched.get("fault"),
                                  "verdict": verdict,
                                  "request_id": rid,
                                  "true_rel_residual": rel},
                        "manifest": {"matrix": str(args.A),
                                     "nparts": int(args.nparts or 0),
                                     "unix_time": time.time()}})
                except OSError as e:
                    sys.stderr.write(f"acg-tpu: --history: {e}\n")
        # the daemon must END the campaign serving a correct answer
        doc = {"matrix": args.A, "b_seed": 12345,
               "rtol": float(args.residual_rtol or 1e-8),
               "maxits": int(args.max_iterations), "timeout": 120.0,
               "request_id": f"chaos-{seed}-final"}
        final, frel, _frid = _chaos_request(base, doc, csr,
                                            verify_solution_dense)
        sys.stderr.write(
            "chaos-serve:\n"
            f"  schedules: {nsched} (seed {seed})\n"
            + "".join(f"  {k}: {v}\n" for k, v in sorted(tally.items())
                      if v)
            + f"  final probe: {final}\n")
        _http_json("POST", f"{base}/shutdown", timeout=10.0)
    finally:
        sup.stop()
    if args.metrics_file:
        try:
            metrics.write_textfile(args.metrics_file)
        except OSError as e:
            sys.stderr.write(f"acg-tpu: --metrics-file: {e}\n")
    if tally["WRONG-ANSWER"] or final == "WRONG-ANSWER":
        return int(ExitCode.WRONG_ANSWER)
    if tally["HANG"] or final not in ("verified",):
        return int(ExitCode.FAILURE)
    return 0


def _wait_serving(base: str, timeout: float) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        try:
            status, doc = _http_json("GET", f"{base}/healthz",
                                     timeout=5.0)
            if status == 200 and doc.get("ok"):
                return True
        except (OSError, ValueError):
            pass
        time.sleep(0.25)
    return False


def _chaos_request(base: str, doc: dict, csr, verify) -> tuple:
    """Fire one campaign request; classify the outcome as ``(verdict,
    rel_residual, request_id)`` -- the echoed request identity lands in
    the verification ledger rows, so a campaign verdict joins against
    the daemon's own access ledger and structured events.  Green
    responses are verified INDEPENDENTLY against the host oracle --
    a green-but-wrong x is the campaign's one unforgivable verdict."""
    b = np.random.default_rng(int(doc["b_seed"])).standard_normal(
        csr.shape[0])
    try:
        status, body = _http_json("POST", f"{base}/solve", doc,
                                  timeout=float(doc["timeout"]) + 30.0)
    except OSError:
        # connection died under us -- the crash-mid-request class
        # (the sent id still identifies the request in daemon logs)
        return "crash-relaunched", None, doc.get("request_id")
    rid = (body.get("request_id") if isinstance(body, dict)
           else None) or doc.get("request_id")
    if status == 200 and body.get("ok"):
        x = np.asarray(body.get("x", []), dtype=np.float64)
        ok, rel = verify(csr, b, x, doc["rtol"])
        return ("verified" if ok else "WRONG-ANSWER"), rel, rid
    if isinstance(body, dict) and body.get("error", {}).get("type"):
        return "typed-error", None, rid
    return "HANG", None, rid
