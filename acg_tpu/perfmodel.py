"""Compiled-program performance observability: XLA cost/memory
introspection, the static communication ledger, the roofline ``--explain``
tier, and the bench regression gate.

The reference's credibility rests on accounting for every byte and flop:
its stats block reports per-op GB/s against a hardware roofline
(``cgcuda.c:1942-1957``), and the SC'25 paper's core claims are
communication-volume arguments (halo bytes vs. allreduce latency).  Our
always-on counters (:func:`acg_tpu.solvers.stats.cg_flops_per_iteration`,
``bench._our_bytes_per_iter``) are ANALYTIC -- hand-derived models that
XLA can silently invalidate through fusion, recomputation, or layout
padding.  This module closes that gap with ground truth from the compiler
itself:

* :func:`analyze_solver` lowers + compiles the EXACT whole-solve program a
  solver dispatches (the ``lower_solve`` hook on :class:`~acg_tpu.solvers.
  jax_cg.JaxCGSolver`, :class:`~acg_tpu.parallel.dist.DistCGSolver` and
  the sharded tiers) and extracts ``compiled.cost_analysis()`` (flops,
  bytes accessed) and ``compiled.memory_analysis()`` (argument / output /
  temp / generated-code HBM bytes).
* :func:`per_iteration_cost` separates the loop body's cost from the
  setup's: HloCostAnalysis counts a while/fori body ONCE, so
  per-iteration = cost(whole program) - cost(setup probe), the probe
  lowered from the solver's own SpMV/dot selection.
* :func:`comm_ledger` asks the solver for its static communication
  ledger (per-neighbour halo payload bytes, psum counts and bytes,
  ring-hop estimates from the mesh shape) -- the ``comm_profile`` hooks
  on the distributed tiers.
* :func:`run_explain` (CLI ``--explain``) fuses all of it into a per-tier
  roofline verdict: predicted iteration time from the modelled HBM,
  comm and dispatch components against the probed bandwidth, measured
  time, attained fraction of the HBM roofline, and the top residual
  (HBM- / comm- / dispatch-bound; the unexplained remainder is
  attributed to compute -- no flops/peak time model is claimed).
* :func:`load_cases` / :func:`compare_cases` / :func:`check_regression`
  diff two ``--stats-json`` captures (or bench row files) case-by-case
  -- ``scripts/bench_diff.py`` and ``bench.py --baseline FILE
  --fail-on-regress PCT`` -- turning the ``BENCH_*.json`` trajectory
  into an enforced gate instead of an eyeballed one.

Everything degrades gracefully: where ``cost_analysis`` /
``memory_analysis`` are unsupported on the running jax version/backend
the report says so and the analytic counters stand alone.  Nothing here
mutates solver state or the compiled programs -- disarmed perfmodel
leaves every solve program byte-identical (asserted at the StableHLO
level in ``tests/test_hlo_structure.py``), and the ``costmodel:`` /
``memory:`` stats sections append strictly after the reference-format
block, like ``timings:``.

jax imports stay inside functions: the bench-diff path (and
``scripts/bench_diff.py --help``) must answer without initialising a
backend.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# Order-of-magnitude per-link inter-chip (ICI) bandwidth for v5e-class
# parts, used only to price the comm ledger's bytes in the --explain
# verdict on TPU backends (off-TPU the "interconnect" is host memory and
# the HBM probe is reused).  A stand-in until a measured ppermute probe
# exists -- the verdict prints the number it used, so a reader can
# re-price.
ICI_GBS = 45.0

UNAVAILABLE = ("analysis unavailable on this jax version/backend")


# -- compiled-program introspection --------------------------------------

def cost_analysis(compiled) -> dict | None:
    """Normalise ``compiled.cost_analysis()`` across jax versions (a
    dict, or one dict per device in older releases) to
    ``{"flops", "bytes_accessed", "output_bytes", "transcendentals"}``.
    None when the backend/version exposes nothing usable.  NOTE: for
    multi-device programs the values are PER DEVICE (XLA analyses the
    per-device module)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 -- unsupported backends raise freely
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    out: dict = {}
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("bytes accessedout{}", "output_bytes"),
                      ("transcendentals", "transcendentals")):
        v = ca.get(key)
        if v is not None:
            v = float(v)
            if v == v:  # drop NaN placeholders
                out[name] = v
    return out or None


def memory_analysis(compiled) -> dict | None:
    """Normalise ``compiled.memory_analysis()`` (CompiledMemoryStats) to
    plain ints: the program's HBM footprint split into argument / output
    / temp / generated-code bytes, plus the aliased-buffer discount and
    the total."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        return None
    if ma is None:
        return None
    out: dict = {}
    for attr, name in (("argument_size_in_bytes", "argument_bytes"),
                       ("output_size_in_bytes", "output_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("alias_size_in_bytes", "alias_bytes"),
                       ("generated_code_size_in_bytes",
                        "generated_code_bytes")):
        v = getattr(ma, attr, None)
        if v is not None:
            out[name] = int(v)
    if not out:
        return None
    out["total_hbm_bytes"] = (out.get("argument_bytes", 0)
                              + out.get("output_bytes", 0)
                              + out.get("temp_bytes", 0)
                              + out.get("generated_code_bytes", 0)
                              - out.get("alias_bytes", 0))
    return out


def analyze_solver(solver, b, x0=None, criteria=None) -> dict:
    """Lower + compile the solver's exact solve program for ``(b, x0,
    criteria)`` and extract the compiler's own cost/memory analysis.

    Returns ``{"available": True, "cost": {...}, "memory": {...}}`` or
    ``{"available": False, "why": "..."}`` -- observability must degrade,
    never raise into a solve path.  Never mutates solver state (the
    ``lower_solve`` hooks re-dispatch the same static configuration a
    real solve uses)."""
    try:
        compiled = solver.lower_solve(b, x0=x0, criteria=criteria).compile()
    except Exception as e:  # noqa: BLE001
        return {"available": False,
                "why": f"lower/compile failed: {type(e).__name__}: {e}"}
    c = cost_analysis(compiled)
    m = memory_analysis(compiled)
    if c is None and m is None:
        return {"available": False, "why": UNAVAILABLE}
    doc: dict = {"available": True}
    if c is not None:
        doc["cost"] = c
    if m is not None:
        doc["memory"] = m
    return doc


def _setup_probe_costs(solver, b, x0) -> dict | None:
    """Cost of the solve program's SETUP phase, compiled standalone from
    the solver's own SpMV/dot selection -- the subtrahend of the
    per-iteration derivation.  Mirrors ``_cg_program`` /
    ``_cg_pipelined_program`` setup (norms, initial residual, and for
    the pipelined variant ``w = A r`` plus the epilogue's fresh ``(r,
    r)``); the leftover ``maximum``/``sqrt`` scalars are noise at vector
    sizes.  Only the direct classic/pipelined single-chip tiers have a
    probe: the replacement/fused tiers restructure the loop, and the
    shard_map program's setup has no standalone form."""
    import jax
    import jax.numpy as jnp

    from acg_tpu.solvers.jax_cg import _scalar_setup, _spmv_fn

    if getattr(solver, "replace_every", 0):
        return None
    if getattr(solver, "problem", None) is not None:
        return None
    kern = solver.kernels
    if isinstance(kern, str) and kern.startswith("fused"):
        return None
    spmv_ = _spmv_fn(kern)
    dot, _sdt = _scalar_setup(b.dtype, solver.precise_dots)
    pipelined = solver.pipelined

    @jax.jit
    def probe(A, b, x0):
        bn = jnp.sqrt(dot(b, b))
        xn = jnp.sqrt(dot(x0, x0))
        r = b - spmv_(A, x0)
        g = dot(r, r)
        out = (bn, xn, jnp.sqrt(g), r)
        if pipelined:
            w = spmv_(A, r)
            out = out + (w, dot(r, r))
        return out

    try:
        compiled = probe.lower(solver._A_program, b, x0).compile()
    except Exception:  # noqa: BLE001
        return None
    return cost_analysis(compiled)


def per_iteration_cost(solver, b, x0=None, criteria=None,
                       whole: dict | None = None) -> dict | None:
    """Compiler-derived per-iteration flops/bytes for the direct
    single-chip tiers: HloCostAnalysis counts a while/fori body ONCE, so
    per-iteration = cost(whole program) - cost(setup probe).  None
    where either half is unavailable.

    Counting conventions differ from the analytic counters BY DESIGN --
    know them before comparing: XLA bills 2 flops per multiply-add over
    PADDED DIA/ELL plane elements where the analytic model bills 3 per
    stored nonzero (the reference's convention, symmetric entries twice,
    ``cgcuda.c:812``), and ``bytes_accessed`` is fusion-aware where the
    analytic model is a fixed pass count.  The cross-check test
    (tests/test_perfmodel.py) pins a small-factor agreement band --
    tight enough to catch silent drift (wrong pass counts, dropped
    terms, double billing), loose enough not to chase convention gaps.
    """
    import jax.numpy as jnp

    if getattr(solver, "problem", None) is not None:
        # the shard_map program's setup has no standalone probe form
        return None
    dtype = solver._solve_dtype()
    b = jnp.asarray(b, dtype=dtype)
    x0 = jnp.zeros_like(b) if x0 is None else jnp.asarray(x0, dtype=dtype)
    if whole is None:
        whole = analyze_solver(solver, b, x0=x0, criteria=criteria)
    if not whole.get("available") or "cost" not in whole:
        return None
    setup = _setup_probe_costs(solver, b, x0)
    if setup is None:
        return None
    out: dict = {}
    for k in ("flops", "bytes_accessed", "transcendentals"):
        w, s = whole["cost"].get(k), setup.get(k)
        if w is not None and s is not None:
            out[k] = max(w - s, 0.0)
    return out or None


# -- communication ledger -------------------------------------------------

def comm_ledger(solver) -> dict | None:
    """The solver's static per-iteration communication ledger (the
    ``comm_profile`` hook on the distributed tiers: per-neighbour halo
    bytes from the halo plans, psum counts/bytes, ICI-hop estimates from
    the mesh shape).  None for single-device solvers; pure host
    arithmetic -- building it cannot perturb the compiled programs."""
    prof = getattr(solver, "comm_profile", None)
    if prof is None:
        return None
    try:
        return prof()
    except Exception as e:  # noqa: BLE001 -- a ledger bug must not sink a solve
        return {"error": f"{type(e).__name__}: {e}"}


def attach(stats, analysis: dict | None, ledger: dict | None = None,
           per_iteration: dict | None = None) -> None:
    """Record an analysis onto ``stats`` -- fills the ``costmodel:`` /
    ``memory:`` sections of the stats block and its ``--stats-json``
    twin.  Append-only by construction: the reference-format block and
    every existing section are untouched (asserted in
    tests/test_hlo_structure.py)."""
    cm: dict = {}
    if analysis is not None:
        if analysis.get("available"):
            cm.update(analysis.get("cost", {}))
        else:
            cm["unavailable"] = analysis.get("why", UNAVAILABLE)
    if per_iteration:
        cm["per_iteration"] = dict(per_iteration)
    if ledger is not None:
        cm["comm"] = ledger
    if cm:
        stats.costmodel.update(cm)
    if analysis is not None and analysis.get("available"):
        mem = analysis.get("memory")
        if mem:
            stats.memory.update(mem)


# -- analytic traffic model (shared with bench.py) ------------------------

def analytic_bytes_per_iteration(nnz: int, n: int, idx_bytes: float,
                                 mat_itemsize: int, vec_itemsize: int,
                                 pipelined: bool) -> float:
    """OUR analytic HBM traffic per CG iteration: matrix reads in the
    matrix storage dtype (+ per-nonzero index bytes) plus the vector
    passes of the loop (15 classic / 21 pipelined -- the pass count
    implied by the measured 335 MB/iter f32 flagship, BASELINE.md) in
    the vector storage dtype.  ``bench._our_bytes_per_iter`` delegates
    here so the harness and the explain tier cannot drift apart."""
    passes = 21 if pipelined else 15
    return nnz * (mat_itemsize + idx_bytes) + passes * n * vec_itemsize


def triad_probe_gbs(nelems: int = 1 << 26, reps: int = 3,
                    attempts: int = 4, lo: float = 20.0,
                    hi: float = 4000.0) -> float:
    """Two-point chained saxpy-triad HBM bandwidth estimate -- the
    estimator ``bench.bandwidth_probe_gbs`` has always used, hoisted
    here so the --explain tier and the bench harness share ONE
    implementation.  ``a = c + s*a``: 2 reads + 1 write per step; the
    16-vs-4-step chained difference cancels per-dispatch latency.
    ``lo``/``hi`` bound plausibility (the bench defaults suit
    accelerator HBM; --explain lowers ``lo`` for small host-CPU
    probes).  Raises RuntimeError when contention keeps the estimate
    implausible for ``attempts`` tries."""
    import functools

    import jax
    import jax.numpy as jnp

    from acg_tpu._platform import device_sync

    n = int(nelems)
    c = jnp.full((n,), 0.5, jnp.float32)
    a = jnp.ones((n,), jnp.float32)

    @functools.partial(jax.jit, static_argnames="k")
    def chain(a, c, k):
        return jax.lax.fori_loop(
            0, k, lambda _, v: c + jnp.float32(1.0000001) * v, a)

    def best(k):
        device_sync(chain(a, c, k))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            device_sync(chain(a, c, k))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    for _ in range(attempts):
        dt = best(16) - best(4)
        if dt > 0:
            bw = 3.0 * n * 4.0 * 12 / dt / 1e9
            if lo <= bw <= hi:
                return bw
        # contention burst corrupted the estimate; retry
    raise RuntimeError("bandwidth probe unstable (two-point estimate "
                       f"implausible after {attempts} attempts)")


def _probe_cache_path() -> str:
    """The on-disk triad-probe sidecar (``ACG_TPU_PROBE_CACHE``
    overrides; default under the XDG cache dir)."""
    p = os.environ.get("ACG_TPU_PROBE_CACHE")
    if p:
        return p
    base = (os.environ.get("XDG_CACHE_HOME")
            or os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "acg-tpu", "probe_cache.json")


def cached_triad_probe_gbs(nelems: int = 1 << 26, use_cache: bool = True,
                           refresh: bool = False, **kw) -> float:
    """:func:`triad_probe_gbs` behind an on-disk, backend-keyed sidecar
    so repeated ``--explain``/bench runs skip the ~1 s re-probe
    (``--no-probe-cache`` forces a fresh measurement).  Keyed by
    ``platform:device_kind:nelems`` -- a CPU figure can never stand in
    for a TPU one, and the small --explain host probe never collides
    with the full-size bench probe.  ``refresh`` re-measures but still
    updates the sidecar (a fresh probe is the best cache entry); cache
    I/O failures degrade to a plain probe."""
    import jax

    dev = jax.devices()[0]
    key = f"{dev.platform}:{dev.device_kind}:n{int(nelems)}"
    path = _probe_cache_path()
    if use_cache and not refresh:
        try:
            with open(path) as f:
                entry = (json.load(f) or {}).get(key)
            if isinstance(entry, dict) and float(entry.get("gbs", 0)) > 0:
                return float(entry["gbs"])
        except (OSError, ValueError, TypeError):
            pass
    bw = triad_probe_gbs(nelems, **kw)
    if use_cache:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            try:
                with open(path) as f:
                    cache = json.load(f)
            except (OSError, ValueError):
                cache = {}
            if not isinstance(cache, dict):
                cache = {}
            cache[key] = {"gbs": float(bw), "unix_time": time.time()}
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(cache, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            pass
    return bw


def _dispatch_seconds(reps: int = 5, dtype=None) -> float:
    """Per-program dispatch latency (a synced noop): the fixed cost a
    whole-solve program pays ONCE, amortised over its iterations in the
    roofline verdict -- on tunneled chips this reaches ~100 ms and
    legitimately dominates short solves (dispatch-bound).  ``dtype``
    follows the solve's VECTOR dtype, the same rule the --profile-ops
    dispatch probe applies (solvers/profile.py): an f32 noop under an
    x64/bf16 config would measure a different-dtype program than the
    solve dispatches."""
    import jax
    import jax.numpy as jnp

    from acg_tpu._platform import device_sync

    dt = jnp.dtype(dtype) if dtype is not None else jnp.float32
    noop = jax.jit(lambda v: v + jnp.asarray(1, v.dtype))
    x = jnp.zeros((8,), dt)
    device_sync(noop(x))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        device_sync(noop(x))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def predicted_overlap_seconds(led: dict, bw_gbs: float | None,
                              ici_gbs: float | None,
                              halo_s: float | None = None) -> dict | None:
    """The fused tier's overlap verdict from its static ledger: price
    the halo payload against the interconnect and the interior-SpMV
    traffic against HBM, then ``exposed = max(0, halo - interior)`` --
    halo latency is only *felt* where the interior rows' work runs out
    before the puts land (the reference's stream-overlap argument,
    restated in ledger terms).  ``hidden_frac`` is directly comparable
    to the measured solve-windowed overlap-efficiency score a --trace
    capture yields.  ``halo_s`` (the commbench calibration's measured
    per-exchange halo seconds) replaces the bytes-over-ici guess when
    given.  None when a needed bandwidth is unknown."""
    ov = led.get("overlap") or {}
    if not bw_gbs or (halo_s is None and not ici_gbs):
        return None
    t_halo = (halo_s if halo_s is not None else
              led.get("halo_bytes_per_iteration", 0) / (ici_gbs * 1e9))
    t_int = ov.get("interior_matrix_bytes", 0) / (bw_gbs * 1e9)
    exposed = max(0.0, t_halo - t_int)
    out = {"halo_s": t_halo, "interior_spmv_s": t_int,
           "exposed_halo_s": exposed,
           "hidden_frac": (1.0 - exposed / t_halo) if t_halo > 0
           else None}
    if halo_s is not None:
        out["halo_source"] = "commbench calibration"
    return out


def classify_bound(measured_s: float, hbm_s: float, comm_s: float,
                   dispatch_s: float) -> tuple[str, dict]:
    """``(verdict, components)``: attribute a measured iteration time to
    its largest modelled component; whatever the byte/comm/dispatch
    model cannot explain is attributed to compute (or an unmodelled
    term -- the verdict is a pointer, not a proof)."""
    comp = {"HBM-bound": max(hbm_s, 0.0),
            "comm-bound": max(comm_s, 0.0),
            "dispatch-bound": max(dispatch_s, 0.0)}
    comp["compute-bound"] = max(measured_s - sum(comp.values()), 0.0)
    verdict = max(comp, key=lambda k: comp[k])
    return verdict, comp


# -- the CLI --explain tier ----------------------------------------------

def _explain_matrix(args):
    """Host CSR for the explain pass: gen: specs synthesized in-process,
    files read via mtxfile.  Explain is an analysis pass over all three
    solver tiers, so it needs the host matrix -- refuse sizes that only
    the direct on-device assembly path could hold."""
    from acg_tpu.errors import AcgError
    from acg_tpu.matrix import SymCsrMatrix

    if args.A.startswith("gen:"):
        from acg_tpu.cli import _gen_direct_min, _parse_gen_spec
        from acg_tpu.io.generators import (irregular_spd_coo, poisson2d_coo,
                                           poisson3d_coo)

        kind, dim, n, N, avg = _parse_gen_spec(args.A)
        if N > _gen_direct_min():
            raise SystemExit(
                f"acg-tpu: --explain analyses host-assembled tiers "
                f"(N={N:,} rows needs the direct on-device path); use a "
                f"smaller gen: spec")
        if kind == "poisson" and getattr(args, "aniso", None) is not None:
            from acg_tpu.io.generators import aniso_poisson2d_coo
            r, c, v, N = aniso_poisson2d_coo(n, args.aniso)
        elif kind == "poisson":
            gen = poisson2d_coo if dim == 2 else poisson3d_coo
            r, c, v, N = gen(n)
        else:
            r, c, v, N = irregular_spd_coo(n, avg_degree=avg,
                                           seed=args.seed)
        A = SymCsrMatrix.from_coo(N, r, c, v)
    else:
        from acg_tpu.io.mtxfile import read_mtx

        try:
            A = SymCsrMatrix.from_mtx(read_mtx(args.A, binary=args.binary))
        except AcgError as e:
            raise SystemExit(f"acg-tpu: {args.A}: {e}")
    return A.to_csr(epsilon=args.epsilon)


def _fmt_bytes(n: float) -> str:
    return f"{n:,.0f} B" if n < 1 << 20 else f"{n / 2**20:,.1f} MiB"


def _explain_tier(name, solver, b, csr, K, bw_gbs, dispatch_s, on_tpu,
                  err, cal: dict | None = None) -> dict | None:
    """Analyze + time one tier and print its explain block.  Returns the
    verdict row (for the optional --stats-json sink), or None when the
    tier failed entirely.

    With a commbench calibration (``cal``, --calibration FILE or a live
    --commbench run) the comm component is priced from the fitted
    alpha-beta model instead of the ring-hop/ICI_GBS guess, the fused
    overlap verdict prices the MEASURED per-exchange halo seconds, and
    the tier's own measured segment decomposition (SpMV-only /
    reduction-only probes from the dispatched TierOps composition)
    replaces the analytic-bytes prediction -- both the calibrated and
    the uncalibrated predicted s/iter are reported so the calibration's
    effect is auditable."""
    from acg_tpu.ops.spmv import matrix_index_bytes, matrix_dtype
    from acg_tpu.solvers.stats import (StoppingCriteria,
                                       cg_flops_per_iteration)

    an = analyze_solver(solver, b)
    per = per_iteration_cost(solver, b, whole=an)
    led = comm_ledger(solver)

    # timed short solve: warmup absorbs the compile, K iterations
    # unbounded (the benchmark protocol's fixed-trip shape)
    solver.stats.tsolve = 0.0
    solver.solve(b, criteria=StoppingCriteria(maxits=K), warmup=1,
                 host_result=False, raise_on_divergence=False)
    t_iter = solver.stats.tsolve / K

    attach(solver.stats, an, ledger=led, per_iteration=per)

    # analytic fallbacks when the compiler analysis is unavailable
    prob = getattr(solver, "problem", None)
    matfree_tables = None
    if prob is not None:
        nnz, n = int(prob.nnz_total), int(prob.n)
        mat_b = int(np.dtype(prob.dtype).itemsize)
        vec_b = int(np.dtype(prob.vdtype).itemsize)
        idx_b = 0.0 if prob.local.format in ("dia", "matfree") else 4.0
        if getattr(prob, "operator", None) is not None:
            matfree_tables = int(prob.operator.table_bytes())
    else:
        A = solver.A
        nnz, n = int(csr.nnz), int(csr.shape[0])
        mat_b = int(np.dtype(matrix_dtype(A)).itemsize)
        vec_b = int(np.dtype(solver._solve_dtype()).itemsize)
        idx_b = matrix_index_bytes(A)
        if hasattr(A, "matfree_apply"):
            matfree_tables = int(A.table_bytes())
    flops_it_analytic = cg_flops_per_iteration(nnz, n, solver.pipelined)
    if matfree_tables is not None:
        # matrix-free operator tier: the roofline's matrix-bytes term
        # goes to (nearly) zero -- the apply reads the O(grid-side)
        # coefficient tables, not nnz * itemsize of planes.  Flops are
        # unchanged (the multiply-adds still happen)
        bytes_it_analytic = (analytic_bytes_per_iteration(
            0, n, 0.0, 0, vec_b, solver.pipelined) + matfree_tables)
    else:
        bytes_it_analytic = analytic_bytes_per_iteration(
            nnz, n, idx_b, mat_b, vec_b, solver.pipelined)
    spec = getattr(solver, "precond_spec", None)
    if spec is not None:
        # reclassify the roofline for PCG: one M^-1 apply per iteration
        # joins both analytic models (the compiler-derived numbers see
        # it automatically -- the apply is IN the program)
        from acg_tpu.precond import (bytes_per_apply, flops_per_apply,
                                     state_bytes)
        mst = getattr(solver, "_mstate", None)
        sb = state_bytes(mst) if mst is not None else 0
        flops_it_analytic += flops_per_apply(spec, n, 3.0 * nnz)
        bytes_it_analytic += bytes_per_apply(
            spec, n, vec_b, nnz * (mat_b + idx_b) + 2 * n * vec_b, sb)
    bytes_it = per.get("bytes_accessed", bytes_it_analytic) if per \
        else bytes_it_analytic

    comm_bytes = 0
    if led and "error" not in led:
        comm_bytes = (led.get("halo_bytes_per_iteration", 0)
                      + led.get("allreduce_bytes_per_iteration", 0))
    ici = ICI_GBS if on_tpu else bw_gbs
    t_hbm = bytes_it / (bw_gbs * 1e9) if bw_gbs else 0.0
    t_comm = comm_bytes / (ici * 1e9) if (comm_bytes and ici) else 0.0
    t_disp = dispatch_s / max(K, 1)
    # the fused tier's overlap model: its ledger declares how much
    # interior-SpMV work is available to hide the halo behind, so the
    # comm verdict prices the EXPOSED halo seconds -- max(0, halo -
    # interior SpMV) -- instead of the full serialised halo time
    overlap = None
    if led and "error" not in led and led.get("overlap"):
        overlap = predicted_overlap_seconds(led, bw_gbs, ici)
        if overlap is not None and ici:
            t_comm = (overlap["exposed_halo_s"]
                      + led.get("allreduce_bytes_per_iteration", 0)
                      / (ici * 1e9))
    t_comm_uncal = t_comm
    predicted_uncal = t_hbm + t_comm_uncal + t_disp

    # -- the calibrated verdict (acg_tpu.commbench) ---------------------
    cal_comm = segs = None
    cal_id = None
    if cal is not None:
        from acg_tpu import commbench
        cal_id = str(cal.get("calibration_id", ""))
        if led and "error" not in led:
            cal_comm = commbench.comm_seconds(cal, led)
            if led.get("overlap"):
                halo_meas = commbench.halo_exchange_seconds(cal, led)
                ov_cal = predicted_overlap_seconds(led, bw_gbs, ici,
                                                   halo_s=halo_meas)
                if ov_cal is not None:
                    overlap = ov_cal
        segs = commbench.segment_decomposition(solver, b)
        if cal_comm is not None:
            # fitted alpha-beta replaces the ring-hop/ICI_GBS guess;
            # the fused ledger still discounts the hidden halo share
            t_comm = (cal_comm["allreduce_s"]
                      + (overlap["exposed_halo_s"]
                         if overlap is not None
                         else cal_comm["halo_s"]))
    verdict, comp = classify_bound(t_iter, t_hbm, t_comm, t_disp)
    predicted = t_hbm + t_comm + t_disp
    if cal is not None and segs and segs.get("available"):
        # measured segments replace the analytic-HBM stand-in: the
        # SpMV segment (exchange included, as dispatched) plus the
        # reduction component (fitted alpha-beta where a mesh ledger
        # exists, the measured psum-ladder probe otherwise) plus the
        # amortised dispatch
        sseg = segs["segments"]
        spmv_seg = sseg.get("spmv", {}).get("s_per_iteration", 0.0)
        red_seg = (cal_comm["allreduce_s"] if cal_comm is not None
                   else sseg.get("reduction", {})
                   .get("s_per_iteration", 0.0))
        predicted = spmv_seg + red_seg + t_disp
    attained = (t_hbm / t_iter) if t_iter > 0 else 0.0

    err.write(f"== explain: {name} ==\n")
    solver.stats.fwrite(err, indent=2)
    if an.get("available") and "cost" in an:
        c = an["cost"]
        err.write(f"  compiler: flops {c.get('flops', 0):,.4g}, bytes "
                  f"accessed {c.get('bytes_accessed', 0):,.4g} per program"
                  f" (loop body counted once by HloCostAnalysis"
                  f"{'; per device' if prob is not None else ''})\n")
    else:
        err.write(f"  compiler: cost {an.get('why', UNAVAILABLE)}\n")
    if per:
        err.write(f"  per-iteration (compiler-derived): flops "
                  f"{per.get('flops', 0):,.4g}, bytes "
                  f"{per.get('bytes_accessed', 0):,.4g}; analytic: flops "
                  f"{flops_it_analytic:,.4g}, bytes "
                  f"{bytes_it_analytic:,.4g}\n")
    else:
        err.write(f"  per-iteration (analytic): flops "
                  f"{flops_it_analytic:,.4g}, bytes "
                  f"{bytes_it_analytic:,.4g}\n")
    if matfree_tables is not None:
        err.write(f"  matrix-free operator: matrix bytes/SpMV "
                  f"{matfree_tables:,} (generated planes read only the "
                  f"coefficient tables; the assembled twin reads "
                  f"{nnz * (mat_b + idx_b):,.0f})\n")
    mem = an.get("memory") if an.get("available") else None
    if mem:
        err.write(f"  memory (HBM footprint): arguments "
                  f"{_fmt_bytes(mem.get('argument_bytes', 0))} + output "
                  f"{_fmt_bytes(mem.get('output_bytes', 0))} + temp "
                  f"{_fmt_bytes(mem.get('temp_bytes', 0))} = "
                  f"{_fmt_bytes(mem.get('total_hbm_bytes', 0))}\n")
    if led and "error" not in led:
        err.write(f"  comm ledger: halo "
                  f"{led.get('halo_bytes_per_iteration', 0):,} B/iter, "
                  f"allreduce {led.get('allreduce_per_iteration', 0)} x "
                  f"{led.get('allreduce_scalars', 0)} scalars "
                  f"({led.get('allreduce_bytes_per_iteration', 0)} B/iter),"
                  f" max {led.get('max_hops', 0)} hop(s) "
                  f"[{led.get('transport', '?')}]\n")
    if overlap is not None:
        ov = led["overlap"]
        hid = overlap.get("hidden_frac")
        err.write(f"  overlap model (interior|border split, "
                  f"{ov.get('interior_rows', 0):,} interior / "
                  f"{ov.get('border_rows', 0):,} border rows): halo "
                  f"{overlap['halo_s']:.3e} s vs interior SpMV "
                  f"{overlap['interior_spmv_s']:.3e} s -> predicted "
                  f"exposed {overlap['exposed_halo_s']:.3e} s/iter"
                  + (f" ({hid:.0%} hidden)" if hid is not None else "")
                  + "\n")
    if segs is not None:
        if segs.get("available"):
            sseg = segs["segments"]
            parts_txt = " + ".join(
                f"{k} {v['s_per_iteration']:.3e}"
                for k, v in sseg.items() if k != "halo")
            halo_txt = (f" (halo {sseg['halo']['s_per_iteration']:.3e}"
                        f" inside spmv)" if "halo" in sseg else "")
            err.write(f"  segments (measured, {segs['reps']} chained "
                      f"reps/probe): {parts_txt} ="
                      f" {segs['explained_s_per_iteration']:.3e} "
                      f"s/iter explained{halo_txt}\n")
        else:
            err.write(f"  segments: unavailable "
                      f"({segs.get('why', '?')})\n")
    if cal is not None:
        if cal_comm is not None:
            err.write(f"  calibrated comm (alpha-beta, {cal_id}): "
                      f"allreduce {cal_comm['allreduce_s']:.3e} + "
                      f"halo[{cal_comm['halo_kind']}] "
                      f"{cal_comm['halo_s']:.3e} s/iter (replaces the "
                      f"ring-hop/ICI stand-in)\n")
        elif led is None:
            err.write(f"  calibrated comm ({cal_id}): no comm ledger "
                      f"on this tier (single device) -- segments "
                      f"carry the calibration\n")
    bw_txt = f"{bw_gbs:,.1f} GB/s" if bw_gbs else "unavailable"
    err.write(f"  roofline: probe {bw_txt}"
              + (f", ici {ici:,.0f} GB/s (stand-in)" if comm_bytes and
                 on_tpu and cal_comm is None else "")
              + f"; predicted {predicted:.3e} s/iter"
              + (f" (measured segments + fitted comm + dispatch; "
                 f"uncalibrated model {predicted_uncal:.3e})"
                 if cal is not None and predicted != predicted_uncal
                 else f" (hbm {t_hbm:.3e} + comm {t_comm:.3e} + "
                      f"dispatch {t_disp:.3e})") + "\n")
    ratio = (predicted / t_iter) if t_iter > 0 else 0.0
    ratio_uncal = (predicted_uncal / t_iter) if t_iter > 0 else 0.0
    err.write(f"  measured {t_iter:.3e} s/iter over {K} iterations; "
              f"attained {attained:.2f}x of HBM roofline; "
              f"predicted/measured {ratio:.2f}x"
              + (f" (uncalibrated {ratio_uncal:.2f}x; calibration "
                 f"{cal_id})" if cal is not None else "")
              + f"; verdict: {verdict}\n\n")

    row = {"tier": name, "measured_s_per_iter": t_iter,
           "predicted_s_per_iter": predicted,
           "attained_roofline_frac": attained, "bound": verdict,
           "components_s": comp}
    if matfree_tables is not None:
        row["matrix_free"] = True
        row["matrix_bytes_per_spmv"] = matfree_tables
    if overlap is not None:
        row["overlap_model"] = overlap
    if cal is not None:
        row["calibration"] = cal_id
        row["uncalibrated_predicted_s_per_iter"] = predicted_uncal
        solver.stats.costmodel["calibration"] = cal_id
        if cal_comm is not None:
            row["calibrated_comm_s"] = cal_comm
    if segs is not None and segs.get("available"):
        row["segments"] = segs
        solver.stats.costmodel["segments"] = segs
    return row


def build_explain_dist_solver(args, csr, nparts, dtype, vec_dtype,
                              operator=None, **solver_kw):
    """The dist analysis tier's construction, shared by
    :func:`run_explain` and the commbench observatory (ONE copy: same
    partition method/seed, same transport resolution -- a commbench
    calibration must describe the very mesh the explain verdict
    prices).  ``operator`` (a matrix-free stencil) forces the band
    partition it requires and arms the matfree local block."""
    from acg_tpu.ops.spmv import prefers_dia
    from acg_tpu.parallel.dist import (DistCGSolver, DistributedProblem,
                                       arm_matfree, resolve_comm)
    from acg_tpu.partition import partition_rows

    method = "band" if operator is not None or prefers_dia(csr) \
        else "graph"
    part = partition_rows(csr, nparts, seed=args.seed, method=method)
    prob = DistributedProblem.build(csr, part, nparts, dtype=dtype,
                                    vector_dtype=vec_dtype)
    if operator is not None:
        arm_matfree(prob, operator)
    return DistCGSolver(prob, pipelined=False,
                        comm=resolve_comm(args.comm),
                        precise_dots=args.precise_dots,
                        kernels=args.kernels, **solver_kw)


def run_explain(args, dtype, vec_dtype) -> int:
    """The CLI ``--explain`` driver: build the system once, then for the
    classic, pipelined and distributed tiers lower + compile the exact
    solve programs, extract compiler cost/memory, build the comm ledger,
    time a short solve, and print the roofline verdict per tier.
    Single-controller analysis pass; exits 0 when at least one tier
    reported."""
    import jax

    err = sys.stderr
    csr = _explain_matrix(args)
    n = csr.shape[0]
    b = np.ones(n)
    K = max(8, min(args.max_iterations, 60))
    on_tpu = jax.default_backend() == "tpu"
    nparts = args.nparts or min(len(jax.devices()), 4)
    # the communication observatory's calibration (a saved --calibration
    # doc or a live --commbench run, loaded/collected by the CLI): the
    # comm components below are then priced from its fitted alpha-beta
    # model and the tiers run measured segment decompositions
    cal = getattr(args, "_calibration", None)
    cal_mismatch_event = None
    if cal is not None:
        from acg_tpu.commbench import KINDS
        src = getattr(args, "_calibration_source", None) \
            or "live --commbench run"
        fitted = [k for k in KINDS
                  if isinstance(cal.get("collectives", {}).get(k), dict)
                  and "alpha_s" in cal["collectives"][k]]
        err.write(f"== explain: calibration ==\n"
                  f"  id {cal.get('calibration_id')} ({src}); fitted "
                  f"kinds: {', '.join(fitted) or 'none'}; benchmarked "
                  f"on a {cal.get('nparts')}-part mesh\n")
        mismatches = []
        if int(cal.get("nparts", 0)) != int(nparts):
            mismatches.append(f"mesh {cal.get('nparts')} parts vs this "
                              f"run's {nparts}")
        cal_backend = cal.get("backend")
        if cal_backend and str(cal_backend) != jax.default_backend():
            mismatches.append(f"backend {cal_backend} vs this run's "
                              f"{jax.default_backend()}")
        if mismatches:
            detail = (f"calibration {cal.get('calibration_id')}: "
                      + "; ".join(mismatches)
                      + " -- fitted latencies may not transfer")
            err.write(f"  WARNING: {detail}\n")
            # the structured twin of the warning (the decision
            # observatory's audit trail): an event the stats-json /
            # history consumers and the metrics textfile can gate on,
            # not just a stderr line
            cal_mismatch_event = {"t": time.time(),
                                  "kind": "calibration-mismatch",
                                  "detail": detail}
            from acg_tpu import metrics, observatory
            metrics.record_event_kind("calibration-mismatch")
            observatory.note_event("calibration-mismatch", detail)
        err.write("\n")
    bw = None
    use_cache = not getattr(args, "no_probe_cache", False)
    try:
        # full-size probe on real HBM; a small (16 MiB/vector) variant
        # elsewhere -- host CPUs move the small triad fast enough, and
        # --explain must stay cheap in CPU test runs.  Behind the
        # backend-keyed sidecar so repeated explain runs skip the
        # re-probe (--no-probe-cache forces one)
        bw = (cached_triad_probe_gbs(use_cache=use_cache) if on_tpu
              else cached_triad_probe_gbs(1 << 22, use_cache=use_cache,
                                          lo=0.5))
    except Exception as e:  # noqa: BLE001
        err.write(f"acg-tpu: bandwidth probe failed ({e}); roofline "
                  f"fractions unavailable\n")
    disp = _dispatch_seconds(dtype=vec_dtype)

    import jax.numpy as jnp

    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    # the matrix-free operator tier (--operator): the single tiers run
    # over the operator itself, the dist tier arms the matfree local
    # block -- the roofline's matrix-bytes term then goes to ~0
    op = None
    if getattr(args, "_operator_spec", None) is not None:
        # ONE construction path with the CLI solve (validation against
        # the gen: matrix, SystemExit wrapping, manifest identity
        # recording) -- a duplicate here would let the two drift
        from acg_tpu.cli import _build_cli_operator
        op = _build_cli_operator(args, n, dtype)
    op_id = op.identity() if op is not None else None
    op_tag = f", operator {op_id}" if op_id else ""

    rows = []
    # under --trace the WHOLE tier sweep runs inside one profiler
    # capture (acg_tpu.tracing): the measured section below then
    # confronts the static ledger with per-op-class device time from
    # the same programs the verdicts describe
    from acg_tpu.tracing import profiler_trace
    with profiler_trace(args.trace):
        # ONE device assembly serves both single-chip tiers (A is immutable;
        # rebuilding it per tier would re-upload every plane)
        A = op if op is not None else device_matrix_from_csr(
            csr, dtype=dtype, format=args.spmv_format)
        for name, pipelined in (("cg", False), ("cg-pipelined", True)):
            try:
                # the session's recovery policy rides along (--recover):
                # lower_solve arms detect exactly like solve(), so the
                # analyzed/timed programs are the configured ones
                solver = JaxCGSolver(A, pipelined=pipelined,
                                     precise_dots=args.precise_dots,
                                     kernels=args.kernels,
                                     vector_dtype=vec_dtype,
                                     recovery=getattr(args, "_recovery",
                                                      None),
                                     precond=getattr(args, "_precond", None))
                pc = getattr(args, "_precond", None)
                row = _explain_tier(
                    f"{name} ({solver.kernels} kernels, {args.dtype}"
                    + (f", precond {pc}" if pc is not None else "")
                    + op_tag + ")",
                    solver, jnp.asarray(b, solver._solve_dtype()), csr, K, bw,
                    disp, on_tpu, err, cal=cal)
                if row:
                    rows.append((row, solver))
            except Exception as e:  # noqa: BLE001 -- one tier must not sink the rest
                err.write(f"acg-tpu: explain tier {name} failed: "
                          f"{type(e).__name__}: {e}\n")

        # one distributed tier: the halo'd multi-part program over however
        # many devices this host exposes (capped -- the ledger and verdict,
        # not scaling, are the point here)
        try:
            solver = build_explain_dist_solver(
                args, csr, nparts, dtype, vec_dtype, operator=op,
                recovery=getattr(args, "_recovery", None),
                precond=getattr(args, "_precond", None))
            pc = getattr(args, "_precond", None)
            row = _explain_tier(f"dist-cg (nparts={nparts}, {solver.kernels} "
                                f"kernels, {args.dtype}"
                                + (f", precond {pc}" if pc is not None
                                   else "") + op_tag + ")", solver, b,
                                csr, K, bw, disp, on_tpu, err, cal=cal)
            if row:
                rows.append((row, solver))
        except Exception as e:  # noqa: BLE001
            err.write(f"acg-tpu: explain tier dist-cg failed: "
                      f"{type(e).__name__}: {e}\n")

    # with a capture: confront the ledgers above with MEASURED device
    # time from the very programs the verdicts describe (acg_tpu.
    # tracing) -- per-op-class seconds, overlap efficiency, and the
    # measured-vs-predicted comm line.  Without --trace this section is
    # absent and the static verdict stands unchanged
    # the mismatch event rides every tier's stats twin, so --stats-json
    # consumers see it next to the comm components it taints
    if cal_mismatch_event is not None:
        for _row, solver in rows:
            solver.stats.events.append(dict(cal_mismatch_event))

    if args.trace:
        _explain_measured(args, rows, K, err)

    # the numerical-health tier's convergence verdict: kappa from the
    # Lanczos tridiagonal of a traced host-oracle solve, the CG-bound
    # predicted iteration count against the measured one, and (when a
    # preconditioner is armed) the kappa(A)/kappa(M^-1 A) effectiveness
    # score -- one tier-independent section (kappa is a property of the
    # operator + preconditioner, not of the execution tier)
    _explain_convergence(args, csr, rows, err)

    if args.stats_json:
        from acg_tpu import telemetry

        try:
            from acg_tpu.commbench import UNCALIBRATED
            for row, solver in rows:
                man = telemetry.run_manifest(
                    metric=f"explain:{row['tier']}", matrix=str(args.A),
                    dtype=args.dtype, explain=row, operator=op_id,
                    calibration=(cal.get("calibration_id")
                                 if cal is not None else UNCALIBRATED))
                telemetry.write_stats_json(args.stats_json, solver.stats,
                                           manifest=man, append=True)
        except OSError as e:
            err.write(f"acg-tpu: {args.stats_json}: {e}\n")
    return 0 if rows else 1


def _explain_measured(args, rows, K: int, err) -> dict | None:
    """The ``--explain`` measured section: parse the capture the tier
    sweep just wrote, print per-op-class device seconds + the
    overlap-efficiency score, and confront the static ledger's
    predicted collective seconds (each tier's comm component x its K
    timed iterations) with the measured ones.  Degrades to a one-line
    why when the capture is unusable (xplane-only schema, failed
    profiler start) -- the static verdict above stands either way."""
    from acg_tpu import tracing

    analysis = tracing.analyze_trace(args.trace)
    err.write("== explain: measured (profiler trace) ==\n")
    for line in tracing.format_analysis(analysis):
        err.write(line + "\n")
    if analysis.get("available"):
        predicted = sum(
            row["components_s"].get("comm-bound", 0.0) * K
            for row, _ in rows)
        err.write(tracing.measured_comm_line(
            analysis, predicted,
            label=f"comm ledger x {K} iters/tier") + "\n")
        # per-KIND confrontation: the commbench alpha-beta fit priced
        # allreduce and halo separately, and the capture now breaks
        # collective seconds out by kind -- confront them kind by kind
        kinds = (analysis.get("collective_kind_seconds_in_solve")
                 or analysis.get("collective_kind_seconds") or {})
        cal_rows = [row for row, _ in rows
                    if row.get("calibrated_comm_s")]
        if kinds and cal_rows:
            pred_ar = sum(r["calibrated_comm_s"]["allreduce_s"] * K
                          for r in cal_rows)
            pred_halo = sum(r["calibrated_comm_s"]["halo_s"] * K
                            for r in cal_rows)
            meas_ar = kinds.get("all_reduce", 0.0)
            meas_halo = sum(v for k, v in kinds.items()
                            if k != "all_reduce")
            err.write(f"  per-kind (commbench fit x {K} iters/"
                      f"calibrated tier): allreduce predicted "
                      f"{pred_ar:.3e} s vs measured {meas_ar:.3e} s; "
                      f"halo predicted {pred_halo:.3e} s vs measured "
                      f"{meas_halo:.3e} s\n")
        # the fused tier's overlap verdict, confronted: the static
        # ledger's predicted hidden fraction vs the capture's measured
        # solve-windowed overlap-efficiency score (same quantity, one
        # modelled, one observed)
        eff = analysis.get("overlap_efficiency")
        for row, _ in rows:
            ov = row.get("overlap_model")
            if ov is None or ov.get("hidden_frac") is None:
                continue
            err.write(f"  overlap verdict [{row['tier']}]: ledger "
                      f"predicts {ov['hidden_frac']:.0%} of halo "
                      f"latency hidden"
                      + (f"; measured solve-windowed "
                         f"overlap-efficiency {eff:.2%}"
                         if eff is not None else
                         "; no measured overlap in this capture")
                      + "\n")
        # the tracing: stats section rides every tier's --stats-json
        # document (one capture covers the whole sweep, so no per-tier
        # op attribution is claimed -- ops rows stay as analyzed)
        # None values (no straggler, overlap n/a) are suppressed, the
        # way tracing.attach builds the section
        compact = {k: analysis[k] for k in
                   ("available", "nfiles", "op_seconds",
                    "collective_seconds", "collective_kind_seconds",
                    "exposed_collective_seconds",
                    "overlap_efficiency", "straggler")
                   if analysis.get(k) is not None}
        for _, solver in rows:
            solver.stats.tracing.update(compact)
    err.write("\n")
    return analysis


def _explain_convergence(args, csr, rows, err) -> dict | None:
    """The ``--explain`` "convergence" section (acg_tpu.health): run
    the eager f64 host oracle traced (cheap at explain sizes), rebuild
    the Lanczos tridiagonal from its (alpha, beta) window, and print
    the kappa estimate + predicted-vs-measured verdict.  The report
    also lands on every tier's ``health:`` stats section so the
    --stats-json twin carries it."""
    import numpy as np

    from acg_tpu import health as health_mod
    from acg_tpu.solvers.host_cg import HostCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    # the oracle is an eager single-threaded f64 loop: bound it by
    # matrix size the way _explain_tier bounds its timed solves by K --
    # --explain is documented as a cheap introspection pass, and a
    # multi-million-nnz oracle solve (x2 under --precond) is not
    if csr.shape[0] > 200_000 or csr.nnz > 2_000_000:
        err.write("== explain: convergence ==\n  (skipped: matrix too "
                  "large for the host-oracle Lanczos estimate; run a "
                  "normal solve with --audit-every + --convergence-log "
                  "for the device-side spectrum report)\n\n")
        return None
    rtol = (args.residual_rtol
            if 0 < args.residual_rtol < 1 else 1e-9)
    crit = StoppingCriteria(maxits=min(max(args.max_iterations, 200),
                                       2000),
                            residual_rtol=rtol)
    b = np.ones(csr.shape[0])
    pc = getattr(args, "_precond", None)
    try:
        kappa_ref = None
        if pc is not None:
            # the effectiveness baseline: kappa(A) from an
            # unpreconditioned oracle run of the same system
            plain = HostCGSolver(csr, trace=4096)
            plain.solve(b, criteria=crit, raise_on_divergence=False)
            ref = health_mod.spectrum_estimate(plain.last_trace)
            kappa_ref = (ref or {}).get("kappa")
        hs = HostCGSolver(csr, trace=4096, precond=pc)
        hs.solve(b, criteria=crit, raise_on_divergence=False)
        rep = health_mod.convergence_report(
            hs.last_trace, hs.stats.niterations, rtol,
            precond=str(pc) if pc is not None else None,
            kappa_ref=kappa_ref)
    except Exception as e:  # noqa: BLE001 -- the verdict must not sink
        err.write(f"acg-tpu: explain convergence verdict failed: "
                  f"{type(e).__name__}: {e}\n")
        return None
    if rep is None:
        err.write("== explain: convergence ==\n  (window too short "
                  "for a Lanczos estimate)\n\n")
        return None
    err.write("== explain: convergence (host-oracle Lanczos "
              "estimate) ==\n")
    err.write(f"  operator {rep['operator']}: lambda "
              f"{rep['lambda_min']:.4g} .. {rep['lambda_max']:.4g}"
              + (f", kappa {rep['kappa']:.4g}" if rep.get("kappa")
                 else ", kappa unavailable (non-positive Ritz value)")
              + f" (m={rep['m']})\n")
    if rep.get("precond_effectiveness") is not None:
        err.write(f"  preconditioner effectiveness: kappa(A) "
                  f"{rep['kappa_unpreconditioned']:.4g} / "
                  f"kappa(M^-1 A) {rep['kappa']:.4g} = "
                  f"{rep['precond_effectiveness']:.2f}x spectrum "
                  f"compression\n")
    pred = rep.get("predicted_iterations")
    if pred is not None:
        meas = rep["measured_iterations"]
        verdict = ("within-bound" if meas <= pred
                   else "OVER-bound (measured exceeds the worst-case "
                        "CG bound: suspect the estimate window or "
                        "numerical trouble)")
        err.write(f"  CG bound at rtol {rep['rtol']:g}: predicted "
                  f"<= {pred} iterations; measured {meas} "
                  f"({meas / pred:.2f}x); verdict: {verdict}\n")
    err.write("\n")
    for _row, solver in rows:
        solver.stats.health.setdefault("spectrum", rep)
    return rep


# -- bench regression gate ------------------------------------------------

# the sentinel row bench.py emits when the backend probe fails (tunnel
# down): value 0 iters/s, not a performance case.  A capture consisting
# of it alone describes a run that never reached hardware -- comparing
# against it can only mislead (ROADMAP Recent notes r05)
UNAVAILABLE_METRIC = "bench_backend_unavailable"


def split_unavailable(cases: dict) -> tuple[dict, bool]:
    """Drop the backend-unavailable sentinel from a case dict; returns
    ``(real_cases, sentinel_was_present)``.  A capture that is ONLY the
    sentinel must exit 2 with a re-baseline message, never enter a
    comparison."""
    had = any(k == UNAVAILABLE_METRIC or
              k.startswith(UNAVAILABLE_METRIC + "|") for k in cases)
    return {k: v for k, v in cases.items()
            if not (k == UNAVAILABLE_METRIC
                    or k.startswith(UNAVAILABLE_METRIC + "|"))}, had


def refuse_unavailable(old: dict, new: dict, old_name: str,
                       new_name: str) -> tuple[dict, dict, bool]:
    """The shared regression-gate guard (check_regression and
    scripts/bench_diff.py): strip the backend-unavailable sentinel from
    both captures and, when either side carried ONLY the sentinel,
    print the re-baseline refusal and flag exit 2.  Returns
    ``(old_cases, new_cases, refused)``."""
    old, old_unavail = split_unavailable(old)
    new, new_unavail = split_unavailable(new)
    refused = (old_unavail and not old) or (new_unavail and not new)
    if refused:
        which = old_name if old_unavail and not old else new_name
        print(f"bench-diff: {which} records {UNAVAILABLE_METRIC} (the "
              f"backend/tunnel was down): no comparable cases -- "
              f"re-baseline before trusting --fail-on-regress",
              file=sys.stderr)
    return old, new, refused


def _doc_case(doc: dict):
    """``(key, value)`` for one --stats-json document: the case key is
    the manifest metric (bench rows) or solver:matrix (CLI solves), the
    value iterations/second from the stats twin.

    A ``/3`` SOAK capture (``stats.soak`` present) is valued at its
    median instead -- p50 iterations over p50 latency -- so two soak
    runs of the same case diff on the steady-state figure, not on a
    cumulative ``tsolve`` whose meaning shifts with the solve count."""
    man = doc.get("manifest") or {}
    st = doc.get("stats") or {}
    metric = man.get("metric")
    if metric is None:
        metric = f"{man.get('solver', 'solve')}:{man.get('matrix', '?')}"
    metric = _precond_keyed(metric, man.get("precond"))
    metric = _batch_keyed(metric, man.get("nrhs"), man.get("block_cg"))
    metric = _operator_keyed(metric, man.get("operator"))
    metric = _calibration_keyed(metric, man.get("calibration"))
    soak = st.get("soak") or {}
    if soak:
        try:
            lat = float((soak.get("latency") or {}).get("p50") or 0.0)
            its = float((soak.get("iterations") or {}).get("p50") or 0.0)
        except (TypeError, ValueError):
            return None
        if lat <= 0 or its <= 0:
            return None
        return str(metric), its / lat
    try:
        tsolve = float(st.get("tsolve", 0.0))
        niter = float(st.get("niterations", 0))
    except (TypeError, ValueError):
        return None
    if tsolve <= 0 or niter <= 0:
        return None
    return str(metric), niter / tsolve


def _precond_keyed(metric, precond) -> str:
    """Fold the precond selection into the case key: a preconditioned
    capture must NEVER silently diff against a plain one -- their
    iterations/second measure different algorithms."""
    metric = str(metric)
    if precond and str(precond) != "none":
        return f"{metric}|precond={precond}"
    return metric


def _batch_keyed(metric, nrhs, block=None) -> str:
    """Fold the batch selection into the case key (the _precond_keyed
    pattern): a B-wide batched (or block-CG) capture measures a
    different program than a single-RHS one and must never silently
    diff against it."""
    metric = str(metric)
    try:
        b = int(nrhs or 0)
    except (TypeError, ValueError):
        b = 0
    if b > 1:
        metric = f"{metric}|nrhs={b}"
        if block:
            metric = f"{metric}|block"
    return metric


def _operator_keyed(metric, operator) -> str:
    """Fold the operator selection into the case key (the
    _precond_keyed pattern): a matrix-free capture runs a different
    program -- zero matrix HBM traffic -- than an assembled one of the
    same system and must never silently diff against it.  Absent keys
    (every assembled capture, and all pre-/11 captures) add nothing, so
    old baselines keep comparing."""
    metric = str(metric)
    op = str(operator or "")
    if op and op != "none":
        return f"{metric}|operator={op}"
    return metric


def _calibration_keyed(metric, calibration) -> str:
    """Fold a commbench calibration id into the case key (the
    _precond_keyed pattern): two captures explained/priced under
    DIFFERENT calibrations measure against different models and must
    never diff silently -- they become distinct, reported-not-gated
    cases.  The ``"uncalibrated"`` sentinel (and absent keys, every
    pre-/10 capture) adds nothing, so old baselines keep comparing."""
    metric = str(metric)
    cal = str(calibration or "")
    if cal and cal != "uncalibrated":
        return f"{metric}|cal={cal}"
    return metric


def _row_case(row: dict):
    """``(key, value)`` for one bench summary row (the JSON lines bench
    prints / BENCH_*.json records)."""
    metric, value = row.get("metric"), row.get("value")
    if metric is None or not isinstance(value, (int, float)):
        return None
    key = _precond_keyed(metric, row.get("precond"))
    key = _batch_keyed(key, row.get("nrhs"), row.get("block"))
    key = _operator_keyed(key, row.get("operator"))
    key = _calibration_keyed(key, row.get("calibration"))
    return key, float(value)


def rows_to_cases(rows) -> dict:
    """Best value per metric over a list of bench row dicts."""
    cases: dict = {}
    for row in rows:
        c = _row_case(row)
        if c is not None:
            cases[c[0]] = max(cases.get(c[0], float("-inf")), c[1])
    return cases


def load_cases(path) -> dict:
    """Parse a capture file into ``{metric: best_value}``.  Accepts
    either format on either side of a diff: ``--stats-json`` documents
    (one indented document, or JSONL-appended as bench writes them) or
    bench summary-row JSONL (BENCH_*.json); non-JSON lines (the ``#``
    commentary bench interleaves) are skipped."""
    with open(path) as f:
        text = f.read()
    objs = []
    try:
        whole = json.loads(text)
        objs = whole if isinstance(whole, list) else [whole]
    except ValueError:
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                objs.append(json.loads(line))
            except ValueError:
                continue
    cases: dict = {}
    for obj in objs:
        if not isinstance(obj, dict):
            continue
        if isinstance(obj.get("parsed"), dict):
            # the growth driver's BENCH_r0N.json wrapper: the row it
            # parsed from the run's stdout rides under "parsed"
            obj = obj["parsed"]
        if isinstance(obj.get("doc"), dict) and "ledger" in obj:
            # a run-history ledger line (acg_tpu.observatory): the
            # stats document rides under "doc"
            obj = obj["doc"]
        c = _doc_case(obj) if "stats" in obj else _row_case(obj)
        if c is not None:
            cases[c[0]] = max(cases.get(c[0], float("-inf")), c[1])
    return cases


def compare_cases(old: dict, new: dict, pct: float
                  ) -> tuple[list[str], int, int]:
    """``(report_lines, nregressed, ncompared)``: case-by-case diff of
    two capture dicts.  A case regresses when its new value falls more
    than ``pct`` percent below the baseline; cases present on only one
    side are reported but never gate (a renamed row must not silently
    pass OR fail -- the no-common-cases outcome is its own exit code)."""
    lines: list[str] = []
    nreg = ncmp = 0
    for key in sorted(set(old) | set(new)):
        if key not in old:
            lines.append(f"bench-diff: {key}: (new case) {new[key]:,.2f}")
            continue
        if key not in new:
            lines.append(f"bench-diff: {key}: baseline-only "
                         f"({old[key]:,.2f}); not gated")
            continue
        ncmp += 1
        o, v = old[key], new[key]
        delta = (v - o) / o * 100.0 if o else 0.0
        if o > 0 and v < o * (1.0 - pct / 100.0):
            nreg += 1
            lines.append(f"bench-diff: {key}: {o:,.2f} -> {v:,.2f} "
                         f"({delta:+.1f}% REGRESSION, threshold "
                         f"-{pct:g}%)")
        else:
            lines.append(f"bench-diff: {key}: {o:,.2f} -> {v:,.2f} "
                         f"({delta:+.1f}%)")
    return lines, nreg, ncmp


def load_baseline_cases(baseline_path) -> dict | None:
    """Baseline cases for the regression gate.  A DIRECTORY is a
    run-history ledger (acg_tpu.observatory, ``--history``): the
    best-known USABLE value per case across every entry, with
    ``bench_backend_unavailable`` captures skipped automatically (the
    BENCH_r05 stale-baseline trap).  Prints the refusal and returns
    None (exit 2) when the ledger is empty or ALL its entries are
    unusable -- an all-unavailable history must force a re-baseline,
    never silently pass."""
    if not os.path.isdir(baseline_path):
        return load_cases(baseline_path)
    from acg_tpu.observatory import load_history_baseline
    cases, all_unavailable, nentries = \
        load_history_baseline(baseline_path)
    if all_unavailable:
        print(f"bench-diff: every capture in {baseline_path} records "
              f"{UNAVAILABLE_METRIC} (the backend/tunnel was down "
              f"for all {nentries} entr{'y' if nentries == 1 else 'ies'}"
              f"): no usable baseline -- re-baseline before trusting "
              f"--fail-on-regress", file=sys.stderr)
        return None
    if not cases:
        print(f"bench-diff: {baseline_path}: no usable ledger entries "
              f"(empty history directory?)", file=sys.stderr)
        return None
    return cases


def check_regression(rows, baseline_path, pct: float) -> int:
    """The ``bench.py --baseline FILE --fail-on-regress PCT`` gate:
    compare this run's emitted rows against the baseline capture --
    a file, or a ``--history`` ledger DIRECTORY (the best usable prior
    capture per case; see :func:`load_baseline_cases`).
    Exit-code contract (shared with scripts/bench_diff.py): 0 = no
    regression, 1 = regression past the threshold, 2 = nothing
    comparable (unreadable baseline / no common cases / an
    all-unavailable history) -- 2 is a failure too, so a renamed
    metric cannot silently green the gate."""
    try:
        old = load_baseline_cases(baseline_path)
    except OSError as e:
        print(f"bench-diff: {baseline_path}: {e}", file=sys.stderr)
        return 2
    if old is None:
        return 2
    old, new, refused = refuse_unavailable(old, rows_to_cases(rows),
                                           str(baseline_path),
                                           "this run")
    if refused:
        return 2
    lines, nreg, ncmp = compare_cases(old, new, pct)
    for ln in lines:
        print(ln, file=sys.stderr)
    if ncmp == 0:
        print("bench-diff: no comparable cases between this run and "
              f"{baseline_path}", file=sys.stderr)
        return 2
    return 1 if nreg else 0
