"""Error codes and exceptions for acg-tpu.

Rebuilds the role of the reference's error layer (``acg/error.h:54-103``,
``acg/error.c:62-142``): a single enum spanning every subsystem, a string
conversion, floating-point-exception reporting, and collective error
agreement so all participants fail together.  The TPU build folds these
into Python exceptions carrying an :class:`ErrorCode`; the FP-exception
check inspects computed arrays for NaN/Inf instead of ``fetestexcept``
(device-side traps are not observable from XLA).
"""

from __future__ import annotations

import enum

import numpy as np


class ErrorCode(enum.IntEnum):
    """Error codes, structurally equivalent to ``ACG_ERR_*`` (error.h:54-103)."""

    SUCCESS = 0
    ERRNO = 1
    EOF = 2
    LINE_TOO_LONG = 3
    INVALID_FORMAT = 4
    INVALID_VALUE = 5
    OVERFLOW = 6
    INDEX_OUT_OF_BOUNDS = 7
    NOT_SUPPORTED = 8
    NOT_CONVERGED = 9
    INVALID_PARTITION = 10
    FEXCEPT = 11
    JAX = 12
    PALLAS = 13
    MESH = 14
    METIS = 15
    MPI = 16
    NOT_CONVERGED_INDEFINITE_MATRIX = 17
    BREAKDOWN = 18


_ERRSTR = {
    ErrorCode.SUCCESS: "success",
    ErrorCode.ERRNO: "system error",
    ErrorCode.EOF: "unexpected end of file",
    ErrorCode.LINE_TOO_LONG: "line exceeds maximum length",
    ErrorCode.INVALID_FORMAT: "invalid file format",
    ErrorCode.INVALID_VALUE: "invalid value",
    ErrorCode.OVERFLOW: "integer overflow",
    ErrorCode.INDEX_OUT_OF_BOUNDS: "index out of bounds",
    ErrorCode.NOT_SUPPORTED: "operation not supported",
    ErrorCode.NOT_CONVERGED: "solver did not converge",
    ErrorCode.INVALID_PARTITION: "invalid partition",
    ErrorCode.FEXCEPT: "floating-point exception",
    ErrorCode.JAX: "JAX runtime error",
    ErrorCode.PALLAS: "Pallas kernel error",
    ErrorCode.MESH: "device mesh error",
    ErrorCode.METIS: "graph partitioner error",
    ErrorCode.MPI: "distributed runtime error",
    ErrorCode.NOT_CONVERGED_INDEFINITE_MATRIX:
        "not converged (indefinite matrix)",
    ErrorCode.BREAKDOWN: "solver breakdown",
}


def errcodestr(code: ErrorCode) -> str:
    """Human-readable description of an error code (cf. ``acgerrcodestr``)."""
    return _ERRSTR.get(code, "unknown error")


class AcgError(Exception):
    """Exception carrying an :class:`ErrorCode` and optional detail."""

    def __init__(self, code: ErrorCode, detail: str = ""):
        self.code = ErrorCode(code)
        msg = errcodestr(self.code)
        if detail:
            msg = f"{msg}: {detail}"
        super().__init__(msg)


class NotConvergedError(AcgError):
    """Raised when a solver fails to meet its stopping criteria."""

    def __init__(self, detail: str = ""):
        super().__init__(ErrorCode.NOT_CONVERGED, detail)


class IndefiniteMatrixError(AcgError):
    """Raised when CG hits (p, Ap) == 0: the matrix is not positive
    definite (the reference's ``ACG_ERR_NOT_CONVERGED_INDEFINITE_MATRIX``
    abort, ``cg.c:304``)."""

    def __init__(self, detail: str = ""):
        super().__init__(ErrorCode.NOT_CONVERGED_INDEFINITE_MATRIX, detail)


class BreakdownError(AcgError):
    """Raised when the breakdown detectors (non-finite residual,
    non-positive (p, Ap) -- acg_tpu.solvers.resilience) flag a solve and
    the recovery policy is exhausted or absent: the numerical state is
    junk and iterating further would only launder NaNs into a
    plausible-looking answer."""

    def __init__(self, detail: str = ""):
        super().__init__(ErrorCode.BREAKDOWN, detail)


class ExitCode(enum.IntEnum):
    """The PROCESS exit-code contract -- one registry for every code
    the CLI, the soak/SLO gates, the fault injector, the erragree
    watchdogs and the supervisor can return, so the supervisor (and
    operators' runbooks) read exit statuses from one table instead of
    grepping four modules.  Codes 86..97 sit in the 64..113 hole shell
    conventions leave free; rendered by ``--buildinfo``."""

    OK = 0
    FAILURE = 1                  # solve/config failure, agreed abort
    NOTHING_COMPARABLE = 2       # bench_diff: no case in common
    BACKEND_UNAVAILABLE = 3      # bounded backend probe failed
    DRIFT = 7                    # --fail-on-drift: EWMA latency drift
    SLO_BREACH = 8               # --fail-on-slo: declared objective
    PEER_DEAD_INJECTED = 86      # peer:dead fault fired on this rank
    CRASH_INJECTED = 94          # crash:exit fault fired (resumable)
    RELAUNCH_BUDGET = 95         # supervisor: relaunch budget spent
    WRONG_ANSWER = 96            # chaos: converged to a wrong answer
    PEER_LOST = 97               # erragree watchdog/heartbeat teardown


# (code, origin, meaning) -- the table --buildinfo renders and the
# supervisor's relaunch policy keys off
EXIT_CONTRACT: tuple = (
    (ExitCode.OK, "everywhere", "success"),
    (ExitCode.FAILURE, "cli/solvers",
     "solve or configuration failure (agreed abort)"),
    (ExitCode.NOTHING_COMPARABLE, "bench_diff",
     "no comparable case between captures"),
    (ExitCode.BACKEND_UNAVAILABLE, "cli",
     "accelerator backend unavailable (bounded probe failed)"),
    (ExitCode.DRIFT, "soak",
     "--fail-on-drift: EWMA solve latency drifted past the gate"),
    (ExitCode.SLO_BREACH, "observatory",
     "--fail-on-slo: a declared service-level objective breached"),
    (ExitCode.PEER_DEAD_INJECTED, "faults",
     "peer:dead fault injector killed this controller"),
    (ExitCode.CRASH_INJECTED, "faults/checkpoint",
     "crash:exit fault injector killed this process between snapshot "
     "commits (relaunch with --resume)"),
    (ExitCode.RELAUNCH_BUDGET, "supervisor",
     "--supervise: relaunch budget exhausted without a converged run"),
    (ExitCode.WRONG_ANSWER, "supervisor",
     "--chaos: a schedule converged (rc 0) but failed the independent "
     "true-residual verification"),
    (ExitCode.PEER_LOST, "erragree",
     "a peer controller died (stage-sync watchdog or solve heartbeat); "
     "this process tore down so the supervisor can relaunch"),
)

# the supervisor's relaunch policy over the contract: which child exit
# codes are worth another attempt from the last snapshot, and which of
# those indicate a LOST PEER (shrink onto the survivor mesh)
RELAUNCHABLE_CODES = frozenset({
    int(ExitCode.FAILURE), int(ExitCode.BACKEND_UNAVAILABLE),
    int(ExitCode.PEER_DEAD_INJECTED), int(ExitCode.CRASH_INJECTED),
    int(ExitCode.PEER_LOST)})
PEER_LOST_CODES = frozenset({
    int(ExitCode.PEER_DEAD_INJECTED), int(ExitCode.PEER_LOST)})


def exit_code_table() -> list:
    """``[(int code, origin, meaning), ...]`` sorted by code -- the
    ``--buildinfo`` rendering of the contract."""
    return [(int(c), o, m)
            for c, o, m in sorted(EXIT_CONTRACT, key=lambda r: int(r[0]))]


def fexcept_str(*arrays) -> str:
    """Report floating-point exceptions observable in computed arrays.

    The reference decodes ``fetestexcept`` flags into a string appended to
    the solver report (``error.c:62-142``, printed at ``cgcuda.c:1971``).
    XLA does not expose trap flags, so we report the observable outcomes:
    NaN / Inf in the arrays produced by the solve.
    """
    flags = []
    for a in arrays:
        a = np.asarray(a)
        if np.isnan(a).any():
            flags.append("invalid (NaN)")
            break
    for a in arrays:
        a = np.asarray(a)
        if np.isinf(a).any():
            flags.append("overflow (Inf)")
            break
    return ", ".join(flags) if flags else "none"
