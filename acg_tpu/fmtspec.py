"""printf-style format-specifier parsing, validation and application.

Rebuilds SURVEY.md component #5, the reference's ``acg/fmtspec.c``:
``fmtspec_parse`` (``fmtspec.h:224``) decomposes a printf conversion
specification into flags / width / precision / length / conversion,
``fmtspecstr`` rebuilds the string, and the driver uses the parse to
validate ``--numfmt`` before any output is produced.  Here the same
surface is a frozen dataclass with :func:`parse` / ``str()`` round-trip,
plus :meth:`FmtSpec.format` so a validated spec can be *applied* --
including C conversions Python's ``%`` operator lacks (``%a``/``%A``
hexadecimal floating point).

Grammar (C11 fprintf): ``%[flags][width][.precision][length]conversion``
with flags ``-+ #0`` (repeatable), width ``\\d+`` or ``*``, precision
``.\\d*`` or ``.*`` (bare ``.`` means 0), length ``hh h l ll j z t L``,
conversion one of ``d i u o x X f F e E g G a A c s p n %``.
"""

from __future__ import annotations

import dataclasses
import enum

__all__ = ["Flags", "FmtSpec", "parse", "parse_prefix", "FmtSpecError",
           "STAR", "FLOAT_CONVERSIONS", "INT_CONVERSIONS"]


class FmtSpecError(ValueError):
    """Invalid format specification (the reference returns EINVAL)."""


class Flags(enum.IntFlag):
    """Conversion flags (``fmtspec.h:38-69``)."""

    NONE = 0
    MINUS = 1 << 0        # '-' left-justify
    PLUS = 1 << 1         # '+' always sign
    SPACE = 1 << 2        # ' ' blank for plus
    NUMBER_SIGN = 1 << 3  # '#' alternative form
    ZERO = 1 << 4         # '0' zero-pad


_FLAG_CHARS = {"-": Flags.MINUS, "+": Flags.PLUS, " ": Flags.SPACE,
               "#": Flags.NUMBER_SIGN, "0": Flags.ZERO}
_FLAG_ORDER = "-+ #0"

# width/precision given as a '*' argument (fmtspec_width_star)
STAR = "*"

# length modifiers, longest first so "ll" wins over "l" (fmtspec.h:135-152)
_LENGTHS = ("hh", "ll", "h", "l", "j", "z", "t", "L")

CONVERSIONS = "diuoxXfFeEgGaAcspn%"
FLOAT_CONVERSIONS = frozenset("fFeEgGaA")
INT_CONVERSIONS = frozenset("diuoxX")


@dataclasses.dataclass(frozen=True)
class FmtSpec:
    """One printf conversion specification (``struct fmtspec``,
    ``fmtspec.h:186-192``)."""

    flags: Flags = Flags.NONE
    width: int | str | None = None      # None, int >= 0, or STAR
    precision: int | str | None = None  # None, int >= 0, or STAR
    length: str = ""                    # "", "hh", "h", "l", "ll", "j", "z", "t", "L"
    conversion: str = "g"

    def __post_init__(self):
        if self.conversion not in CONVERSIONS or len(self.conversion) != 1:
            raise FmtSpecError(f"invalid conversion {self.conversion!r}")
        if self.length and self.length not in _LENGTHS:
            raise FmtSpecError(f"invalid length modifier {self.length!r}")
        for name, v in (("width", self.width), ("precision", self.precision)):
            if not (v is None or v == STAR
                    or (isinstance(v, int) and v >= 0)):
                raise FmtSpecError(f"invalid {name} {v!r}")

    # -- classification ---------------------------------------------------

    @property
    def is_float(self) -> bool:
        return self.conversion in FLOAT_CONVERSIONS

    @property
    def is_integer(self) -> bool:
        return self.conversion in INT_CONVERSIONS

    @property
    def needs_star_args(self) -> bool:
        return STAR in (self.width, self.precision)

    # -- string round-trip (fmtspecstr) ------------------------------------

    def __str__(self) -> str:
        out = ["%"]
        out += [c for c in _FLAG_ORDER if _FLAG_CHARS[c] & self.flags]
        if self.width is not None:
            out.append(str(self.width))
        if self.precision is not None:
            out.append(f".{self.precision}")
        out.append(self.length)
        out.append(self.conversion)
        return "".join(out)

    # -- application -------------------------------------------------------

    def format(self, value, *star_args) -> str:
        """Apply the spec to one value (the printf call the reference
        leaves to libc).  ``*star_args`` supply ``*`` width/precision in
        printf argument order."""
        width, precision = self.width, self.precision
        star = list(star_args)
        if width == STAR:
            width = int(star.pop(0))
        if precision == STAR:
            precision = int(star.pop(0))
        if star:
            raise FmtSpecError(f"{len(star)} unused star argument(s)")
        conv = self.conversion
        if conv == "%":
            return self._pad("%", width)
        if conv == "n":
            return ""  # "Nothing printed" (fmtspec.h:177)
        if conv in "aA":
            return self._pad(self._hexfloat(float(value), precision, conv),
                             width)
        if conv == "p":
            return self._pad(hex(int(value)), width)
        # Python's % implements the rest, but rejects C length modifiers
        # and 'i'/'u'; strip/translate those (they change the C argument
        # type, which Python numbers subsume)
        pyconv = {"i": "d", "u": "d", "F": "f"}.get(conv, conv)
        flags = "".join(c for c in _FLAG_ORDER if _FLAG_CHARS[c] & self.flags)
        spec = "%" + flags + ("" if width is None else str(width)) + \
            ("" if precision is None else f".{precision}") + pyconv
        if conv in "diu":
            value = int(value)
        elif self.is_float:
            value = float(value)
        return spec % value

    def _pad(self, s: str, width) -> str:
        if width is None or len(s) >= width:
            return s
        if self.flags & Flags.MINUS:
            return s + " " * (width - len(s))
        if self.flags & Flags.ZERO and self.conversion in "aAp":
            # zero padding goes after the sign and the 0x prefix;
            # inf/nan (no 0x) pad with spaces like printf
            head = len(s) - len(s.lstrip("+- "))
            if s[head:head + 2].lower() == "0x":
                head += 2
                return s[:head] + "0" * (width - len(s)) + s[head:]
        return " " * (width - len(s)) + s

    def _hexfloat(self, v: float, precision, conv: str) -> str:
        """C17 %a: [-]0xh.hhhp±d.  float.hex() already emits the C
        shape for normal numbers; handle sign flags, precision
        rounding, and specials here."""
        import math

        sign = "-" if math.copysign(1.0, v) < 0 else (
            "+" if self.flags & Flags.PLUS else (
                " " if self.flags & Flags.SPACE else ""))
        a = abs(v)
        if math.isnan(a):
            body = "nan"
        elif math.isinf(a):
            body = "inf"
        else:
            h = a.hex()  # "0x1.921fb54442d18p+1" / "0x0.0p+0"
            mant, exp = h.split("p")
            if precision is not None:
                # round the fractional hex digits to `precision` places
                intpart, frac = (mant.split(".") + [""])[:2]
                scaled = int(intpart[2:] + frac, 16)
                drop = 4 * (len(frac) - precision)
                if drop > 0:
                    # round to nearest, ties to even (what printf does)
                    rem = scaled & ((1 << drop) - 1)
                    half = 1 << (drop - 1)
                    scaled >>= drop
                    if rem > half or (rem == half and scaled & 1):
                        scaled += 1
                elif drop < 0:
                    scaled <<= -drop  # pad with trailing hex zeros
                digits = hex(scaled)[2:].rjust(precision + 1, "0")
                if precision == 0:
                    head, tail = digits, ""
                else:
                    head, tail = digits[:-precision] or "0", digits[-precision:]
                mant = "0x" + head + ("." + tail if tail else "")
            elif "." in mant:
                # no precision: exact digits, trailing zeros dropped
                # (glibc's choice; "0x1.8000...0p+0" -> "0x1.8p+0")
                mant = mant.rstrip("0").rstrip(".")
            body = f"{mant}p{int(exp):+d}"
        out = sign + body
        return out.upper() if conv == "A" else out


def parse_prefix(s: str, pos: int = 0) -> tuple[FmtSpec, int]:
    """Parse one conversion specification starting at ``s[pos]``; return
    the spec and the index one past it (the reference's ``endptr``,
    ``fmtspec.h:219-231``)."""
    n = len(s)
    if pos >= n or s[pos] != "%":
        raise FmtSpecError(f"expected '%' at position {pos} in {s!r}")
    i = pos + 1
    flags = Flags.NONE
    while i < n and s[i] in _FLAG_CHARS:
        flags |= _FLAG_CHARS[s[i]]
        i += 1
    width: int | str | None = None
    if i < n and s[i] == "*":
        width, i = STAR, i + 1
    else:
        j = i
        while j < n and s[j].isdigit():
            j += 1
        if j > i:
            width, i = int(s[i:j]), j
    precision: int | str | None = None
    if i < n and s[i] == ".":
        i += 1
        if i < n and s[i] == "*":
            precision, i = STAR, i + 1
        else:
            j = i
            while j < n and s[j].isdigit():
                j += 1
            # a bare '.' means precision 0 (fmtspec.h:120-122)
            precision, i = (int(s[i:j]) if j > i else 0), j
    length = ""
    for mod in _LENGTHS:
        if s.startswith(mod, i):
            length, i = mod, i + len(mod)
            break
    if i >= n or s[i] not in CONVERSIONS:
        got = s[i] if i < n else "<end>"
        raise FmtSpecError(f"invalid conversion character {got!r} in {s!r}")
    return FmtSpec(flags=flags, width=width, precision=precision,
                   length=length, conversion=s[i]), i + 1


def parse(s: str) -> FmtSpec:
    """Parse a string that must be exactly one conversion specification
    (how the driver validates ``--numfmt``)."""
    spec, end = parse_prefix(s, 0)
    if end != len(s):
        raise FmtSpecError(f"trailing characters after conversion: {s[end:]!r}")
    return spec
