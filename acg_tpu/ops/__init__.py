from acg_tpu.ops.spmv import (DeviceMatrix, DiaMatrix, EllMatrix, CooMatrix,  # noqa: F401
                              spmv, device_matrix_from_csr)
