"""Device-resident sparse matrix formats and SpMV for TPU.

The reference's device SpMV is a merge-based CSR kernel tuned for GPU warp
semantics (``cg-kernels-cuda.cu:340-441``).  That idiom does not map to a
vector architecture; its *goal* -- load balance across irregular rows --
maps on TPU to row padding / binning (SURVEY.md section 7 "hard parts").
Two formats are provided:

* :class:`EllMatrix` -- ELLPACK: row-padded (n, K) value/column planes.
  For stencil-like matrices (Poisson: K<=5 in 2D, K<=7 in 3D) padding waste
  is tiny and SpMV becomes K fused gather-multiply-accumulates.
* :class:`CooMatrix` -- sorted COO + segment-sum: the general fallback for
  matrices with skewed row lengths where ELL padding would blow up memory.
* :class:`DiaMatrix` -- diagonal storage: y = sum_d data[d] * shift(x, d)
  with *static* offsets.  For banded matrices (stencils in natural order,
  or anything after RCM reordering) SpMV becomes pure VPU multiply-adds on
  statically-sliced vectors -- NO gathers at all.  Measured on TPU this is
  ~30x faster than the ELL gather path on poisson2d n=2048; XLA gathers
  with arbitrary indices do not vectorise on TPU.  A hand-written Pallas
  kernel (:func:`acg_tpu.ops.pallas_kernels.dia_spmv`) shaves a further
  ~1.2x off the DIA path on TPU by reading x through VMEM once instead of
  once per diagonal (solver flag ``kernels="pallas"``).

Format choice is automatic in :func:`device_matrix_from_csr` from the
sparsity structure (diagonal count, then row-length histogram), computed at
load time (same decision the reference makes statically by choosing its
merge-CSR kernel).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Union

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=["data", "cols"], meta_fields=["nrows", "ncols_padded"])
@dataclasses.dataclass
class EllMatrix:
    """ELLPACK storage: data[i, k] * x[cols[i, k]] summed over k.

    Padding entries have data == 0 and cols == 0 (a harmless gather).
    ``ncols_padded`` is the length of the x vector this matrix multiplies
    (owned + ghost entries for partitioned off-diagonal blocks).
    """

    data: jax.Array  # (nrows, K) float
    cols: jax.Array  # (nrows, K) int32
    nrows: int
    ncols_padded: int

    @property
    def K(self) -> int:
        return self.data.shape[1]


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=["rows", "cols", "vals"],
                   meta_fields=["nrows", "ncols_padded"])
@dataclasses.dataclass
class CooMatrix:
    """Row-sorted COO; SpMV via segment_sum (general irregular fallback)."""

    rows: jax.Array  # (nnz,) int32, sorted ascending
    cols: jax.Array  # (nnz,) int32
    vals: jax.Array  # (nnz,) float
    nrows: int
    ncols_padded: int


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=["data"],
                   meta_fields=["offsets", "nrows", "ncols_padded"])
@dataclasses.dataclass
class DiaMatrix:
    """Diagonal (DIA) storage: ``data[d][i] = A[i, i + offsets[d]]``.

    SpMV is a sum of elementwise products against statically-shifted views
    of x -- fully vectorised on the VPU, no gathers.  ``offsets`` is a
    static tuple so each shift compiles to a static slice.

    ``data`` is a tuple of separate (nrows,) planes rather than one
    (ndiags, nrows) array: 1-D jit parameters keep their trivial layout,
    while a 2-D parameter was measured 2-3x slower inside the solve loop
    on TPU (XLA cannot re-lay-out runtime parameters the way it does
    compile-time constants).
    """

    data: tuple            # ndiags x (nrows,) float planes
    offsets: tuple         # (ndiags,) static ints, ascending
    nrows: int
    ncols_padded: int

    @property
    def dtype(self):
        return self.data[0].dtype


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=["bin_rows", "bin_data", "bin_cols",
                                "tail_rows", "tail_cols", "tail_vals"],
                   meta_fields=["bin_ks", "nrows", "ncols_padded"])
@dataclasses.dataclass
class BinnedEllMatrix:
    """Length-binned ELL: rows grouped by nnz into near-tight width
    bins, each bin a dense (m_b, K_b) gather-multiply-reduce, plus a
    sorted-COO tail for hub rows wider than the largest bin.

    The TPU answer to the reference's merge-based CSR kernel
    (``cg-kernels-cuda.cu:340-441``): its goal -- load balance across
    wildly skewed row lengths -- maps on a vector architecture to
    eliminating both the padding waste of plain ELL (power-law tails
    make K_max huge) and the per-nnz ``segment_sum`` machinery of COO,
    which costs as much as the gather itself (measured 177 ms vs 130 ms
    per 8.3M-nnz pass on v5e).  Each bin reduces over a STATIC K_b axis
    (no segment ids), and per-bin results scatter-add into y at unique
    row positions (~n ops, not ~nnz).  Geometric bin boundaries bound
    padding at ~1.33x.
    """

    bin_rows: tuple   # per bin: (m_b,) int32 original row ids
    bin_data: tuple   # per bin: (m_b, K_b) values
    bin_cols: tuple   # per bin: (m_b, K_b) int32 (padding -> col 0, val 0)
    tail_rows: jax.Array  # (t,) int32 sorted; hub-row leftovers
    tail_cols: jax.Array  # (t,) int32
    tail_vals: jax.Array  # (t,)
    bin_ks: tuple     # static K_b per bin
    nrows: int
    ncols_padded: int

    @property
    def dtype(self):
        if self.bin_data:
            return self.bin_data[0].dtype
        return self.tail_vals.dtype


DeviceMatrix = Union[EllMatrix, CooMatrix, DiaMatrix, BinnedEllMatrix]

# A fifth member by protocol rather than by type: matrix-free operators
# (acg_tpu.ops.operator) expose ``matfree_apply``/``matfree_diagonal``/
# ``matfree_nnz`` and are accepted everywhere a DeviceMatrix is -- the
# dispatchers below check the protocol FIRST, so an operator never
# falls through to a stored-plane path that does not exist for it.


def _is_matfree(A) -> bool:
    return hasattr(A, "matfree_apply")

# geometric (x1.5) bin widths: padding bounded at ~1.33x, ~18 bins max
BELL_WIDTHS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128, 192,
               256, 384, 512)


def binned_ell_from_csr(csr, dtype=jnp.float32,
                        widths=BELL_WIDTHS) -> BinnedEllMatrix:
    """Host-side CSR -> length-binned ELL (+ sorted-COO hub tail)."""
    nrows, ncols = csr.shape
    indptr = np.asarray(csr.indptr)
    row_nnz = np.diff(indptr)
    widths = np.asarray(widths)
    # bin index per row: first width >= nnz; hubs (> max width) -> tail
    bidx = np.searchsorted(widths, row_nnz)
    bin_rows, bin_data, bin_cols, bin_ks = [], [], [], []
    for b, K in enumerate(widths):
        rows = np.flatnonzero(bidx == b).astype(np.int32)
        if rows.size == 0:
            continue
        m = rows.size
        data = np.zeros((m, K), dtype=np.float64)
        cols = np.zeros((m, K), dtype=np.int32)
        nnz_b = row_nnz[rows]
        flat_r = np.repeat(np.arange(m), nnz_b)
        flat_p = (np.arange(nnz_b.sum())
                  - np.repeat(np.cumsum(nnz_b) - nnz_b, nnz_b))
        src = (np.repeat(indptr[rows], nnz_b)
               + flat_p).astype(np.int64)
        data[flat_r, flat_p] = np.asarray(csr.data)[src]
        cols[flat_r, flat_p] = np.asarray(csr.indices)[src]
        bin_rows.append(jnp.asarray(rows))
        bin_data.append(jnp.asarray(data, dtype=dtype))
        bin_cols.append(jnp.asarray(cols))
        bin_ks.append(int(K))
    hub = np.flatnonzero(bidx >= widths.size)
    t_rows = np.repeat(hub, row_nnz[hub]).astype(np.int32)
    t_src = np.concatenate([np.arange(indptr[r], indptr[r + 1])
                            for r in hub]) if hub.size else np.zeros(0, np.int64)
    return BinnedEllMatrix(
        bin_rows=tuple(bin_rows), bin_data=tuple(bin_data),
        bin_cols=tuple(bin_cols),
        tail_rows=jnp.asarray(t_rows),
        tail_cols=jnp.asarray(np.asarray(csr.indices)[t_src], dtype=jnp.int32),
        tail_vals=jnp.asarray(np.asarray(csr.data)[t_src], dtype=dtype),
        bin_ks=tuple(bin_ks), nrows=nrows, ncols_padded=ncols)


def csr_diag_offsets(csr) -> np.ndarray:
    """Distinct diagonal offsets (col - row) of a scipy sparse matrix,
    ascending.  Works for rectangular blocks (e.g. owned x ghost)."""
    coo = csr.tocoo()
    return np.unique(coo.col.astype(np.int64) - coo.row.astype(np.int64))


def dia_planes_fixed(csr, offsets, nrows_pad: int) -> np.ndarray:
    """Host-side CSR -> (ndiags, nrows_pad) DIA planes for a *given* offset
    set (used for mesh-uniform stacking: every part stores the union of all
    parts' offsets, missing diagonals as zero planes)."""
    offsets = np.asarray(offsets, dtype=np.int64)
    coo = csr.tocoo()
    diag = coo.col.astype(np.int64) - coo.row.astype(np.int64)
    dmap = np.searchsorted(offsets, diag)
    if diag.size and ((dmap >= offsets.size) | (offsets[dmap % offsets.size] != diag)).any():
        raise ValueError("matrix has diagonals outside the given offset set")
    data = np.zeros((offsets.size, nrows_pad), dtype=np.float64)
    data[dmap, coo.row] = coo.data
    return data


def acc_dtype(dtype):
    """Accumulation dtype for reductions over ``dtype`` storage: sub-f32
    storage (bf16) accumulates in f32 -- the converts ride the VPU for
    free while HBM traffic stays half-width -- wider dtypes accumulate
    natively.  The storage/compute split of the mixed-precision tier
    (the designed deviation from the reference's all-f64 arithmetic,
    ``comm.h:180-183``; SURVEY.md section 7 "hard parts")."""
    return jnp.promote_types(dtype, jnp.float32)


def dia_mv(planes, offsets, nrows: int, x: jax.Array) -> jax.Array:
    """y = A @ x for DIA planes (each (nrows,)) with static ``offsets``:
    ``y[i] = sum_d planes[d][i] * x[i + offsets[d]]``.  Pure VPU
    multiply-adds on statically-sliced views -- no gathers.  ``x`` may be
    shorter or longer than ``nrows`` (rectangular blocks); out-of-range
    entries read padded zeros.  Sub-f32 storage accumulates in f32 and
    rounds once on the final store (:func:`acc_dtype`)."""
    L = max(0, -min(offsets))
    R = max(0, max(offsets) + nrows - x.shape[0])
    adt = acc_dtype(x.dtype)
    xp = jnp.pad(x, (L, R))
    y = jnp.zeros((nrows,), dtype=adt)
    for plane, off in zip(planes, offsets):
        y = y + (plane.astype(adt)
                 * jax.lax.dynamic_slice(xp, (L + off,), (nrows,)).astype(adt))
    return y.astype(x.dtype)


def dia_mv_roll(planes, offsets, x: jax.Array) -> jax.Array:
    """``y = A @ x`` for square DIA planes via CYCLIC shifts:
    ``y = sum_d planes[d] * roll(x, -offsets[d])``.

    Equivalent to :func:`dia_mv` when every plane is zero at positions
    whose column would fall outside ``[0, n)`` -- true by construction
    for planes built by :func:`dia_from_csr` / :func:`dia_planes_fixed`
    / the stencil generators, since no matrix entry exists off the end
    of a diagonal: the wrapped values multiply structural zeros.

    This is the SPMD-native formulation of the distributed stencil SpMV:
    under ``jit`` over a sharded ``x``, XLA compiles each roll into
    boundary ``collective-permute``s -- the halo exchange of the
    reference's ``acghalo`` engine (``halo.c``), *derived by the
    partitioner* instead of hand-planned (verified: the 8-way sharded
    3D-Poisson program contains collective-permutes and zero
    all-gathers).  Padding-based shifts (:func:`dia_mv`) would instead
    break the even sharding and force gathers.
    """
    adt = acc_dtype(x.dtype)
    y = jnp.zeros_like(x, dtype=adt)
    for plane, off in zip(planes, offsets):
        y = y + plane.astype(adt) * jnp.roll(x, -off).astype(adt)
    return y.astype(x.dtype)


def dia_from_csr(csr, dtype=jnp.float32) -> DiaMatrix:
    """Convert a scipy CSR matrix to DIA planes (host-side)."""
    nrows, ncols = csr.shape
    coo = csr.tocoo()
    diag = coo.col.astype(np.int64) - coo.row.astype(np.int64)
    offsets = np.unique(diag)
    data = np.zeros((offsets.size, nrows), dtype=np.float64)
    dmap = np.searchsorted(offsets, diag)
    data[dmap, coo.row] = coo.data
    return DiaMatrix(data=tuple(jnp.asarray(data[d], dtype=dtype)
                                for d in range(offsets.size)),
                     offsets=tuple(int(o) for o in offsets),
                     nrows=nrows, ncols_padded=ncols)


def ell_planes_from_csr(rowptr, colidx, vals, nrows_pad: int,
                        pad_k: int | None = None):
    """Host-side CSR -> zero-padded ELL planes (numpy), rows padded to
    ``nrows_pad`` and width to ``pad_k`` (used for mesh-uniform stacking)."""
    rowptr = np.asarray(rowptr)
    colidx = np.asarray(colidx)
    vals = np.asarray(vals)
    nrows = len(rowptr) - 1
    row_nnz = np.diff(rowptr)
    K = int(row_nnz.max()) if row_nnz.size else 0
    if pad_k is not None:
        K = max(K, pad_k)
    K = max(K, 1)
    data = np.zeros((nrows_pad, K), dtype=np.float64)
    cols = np.zeros((nrows_pad, K), dtype=np.int32)
    # vectorised fill: position of each nz within its row
    rows = np.repeat(np.arange(nrows), row_nnz)
    pos = np.arange(len(colidx)) - np.repeat(rowptr[:-1], row_nnz)
    data[rows, pos] = vals
    cols[rows, pos] = colidx
    return data, cols


def ell_from_csr(rowptr, colidx, vals, nrows: int, ncols: int,
                 dtype=jnp.float32, pad_k: int | None = None) -> EllMatrix:
    """Convert host CSR arrays to a device EllMatrix."""
    data, cols = ell_planes_from_csr(rowptr, colidx, vals, nrows, pad_k)
    return EllMatrix(data=jnp.asarray(data, dtype=dtype),
                     cols=jnp.asarray(cols), nrows=nrows, ncols_padded=ncols)


def coo_from_csr(rowptr, colidx, vals, nrows: int, ncols: int,
                 dtype=jnp.float32) -> CooMatrix:
    rowptr = np.asarray(rowptr)
    row_nnz = np.diff(rowptr)
    rows = np.repeat(np.arange(nrows, dtype=np.int32), row_nnz)
    return CooMatrix(rows=jnp.asarray(rows),
                     cols=jnp.asarray(np.asarray(colidx), dtype=jnp.int32),
                     vals=jnp.asarray(np.asarray(vals), dtype=dtype),
                     nrows=nrows, ncols_padded=ncols)


# shared DIA-eligibility thresholds (device_matrix_from_csr, CLI partition
# auto-method; dist._stack_local_blocks keeps headroom over MAX_DIAGS
# because the union of per-part offset sets can exceed any one count)
MAX_DIAGS = 64
DIA_WASTE_LIMIT = 3.0


def count_diagonals(csr) -> int:
    return int(csr_diag_offsets(csr).size)


def prefers_dia(csr, max_diags: int = MAX_DIAGS,
                waste_limit: float = DIA_WASTE_LIMIT) -> bool:
    """True when the matrix is banded enough that gather-free DIA storage
    (and hence a contiguous band partition) is the right TPU choice."""
    if not csr.nnz:
        return False
    ndiags = count_diagonals(csr)
    return ndiags <= max_diags and ndiags * csr.shape[0] / csr.nnz <= waste_limit


def device_matrix_from_csr(csr, dtype=jnp.float32, format: str = "auto",
                           ell_waste_limit: float = 3.0,
                           dia_waste_limit: float = DIA_WASTE_LIMIT,
                           max_diags: int = MAX_DIAGS) -> DeviceMatrix:
    """Pick DIA, ELL or COO from the sparsity structure of a scipy CSR.

    DIA wins when the matrix is banded (few distinct diagonals, bounded
    fill waste) -- the common case for stencil/FEM matrices in natural or
    RCM order, and by far the fastest SpMV on TPU (no gathers).  Otherwise
    ELL when padding waste (K_max * n / nnz) stays below
    ``ell_waste_limit``, else segment-sum COO.
    """
    nrows, ncols = csr.shape
    row_nnz = np.diff(csr.indptr)
    K = int(row_nnz.max()) if nrows else 0
    nnz = csr.nnz
    if format == "auto":
        ndiags = count_diagonals(csr)
        if (ndiags <= max_diags and nnz
                and ndiags * nrows / nnz <= dia_waste_limit):
            format = "dia"
        else:
            waste = (K * nrows / nnz) if nnz else 1.0
            # skewed row lengths: binned ELL beats COO by replacing the
            # per-nnz segment_sum (as expensive as the gather itself)
            # with static per-bin reductions (measured ~2x -- BASELINE)
            format = "ell" if waste <= ell_waste_limit else "bell"
    if format == "dia":
        return dia_from_csr(csr, dtype)
    if format == "ell":
        return ell_from_csr(csr.indptr, csr.indices, csr.data, nrows, ncols, dtype)
    if format == "bell":
        return binned_ell_from_csr(csr, dtype)
    if format == "coo":
        return coo_from_csr(csr.indptr, csr.indices, csr.data, nrows, ncols, dtype)
    raise ValueError(f"unknown device matrix format {format!r}")


def matrix_dtype(A: DeviceMatrix):
    """Value-storage dtype of any device matrix format."""
    if hasattr(A, "dtype"):
        return A.dtype
    if hasattr(A, "data"):
        return A.data.dtype
    return A.vals.dtype


def matrix_index_bytes(A: DeviceMatrix) -> float:
    """Index bytes read per stored nonzero during SpMV (DIA: none;
    ELL-family: one int32 column; COO: row + column; binned ELL: the
    nnz-weighted mix of its 4 B bins and 8 B hub tail; matrix-free
    operators: none -- no stored nonzeros exist)."""
    if _is_matfree(A) or isinstance(A, DiaMatrix):
        return 0.0
    if isinstance(A, CooMatrix):
        return 8.0
    if isinstance(A, BinnedEllMatrix):
        bins = sum(int(d.size) for d in A.bin_data)  # padded entries read too
        tail = int(A.tail_vals.size)
        total = bins + tail
        return (4.0 * bins + 8.0 * tail) / total if total else 4.0
    return 4.0


def spmv(A: DeviceMatrix, x: jax.Array) -> jax.Array:
    """y = A @ x for a device sparse matrix (jit-safe, differentiable).

    Wrapped in a `jax.named_scope` so profiler traces show the SpMV as a
    labelled range (the reference's NVTX tier, ``cgcuda.c:771-801``).
    """
    with jax.named_scope(f"spmv_{type(A).__name__}"):
        return _spmv(A, x)


def _binned_ell_mv(A: BinnedEllMatrix, x: jax.Array) -> jax.Array:
    adt = acc_dtype(x.dtype)
    y = jnp.zeros((A.nrows,), dtype=adt)
    for rows, data, cols in zip(A.bin_rows, A.bin_data, A.bin_cols):
        contrib = jnp.einsum("mk,mk->m", data, x[cols],
                             preferred_element_type=adt)
        # each row lives in exactly one bin: unique scatter positions
        y = y.at[rows].add(contrib, unique_indices=True)
    if A.tail_rows.size:
        prod = A.tail_vals.astype(adt) * x[A.tail_cols].astype(adt)
        y = y + jax.ops.segment_sum(prod, A.tail_rows,
                                    num_segments=A.nrows,
                                    indices_are_sorted=True)
    return y.astype(x.dtype)


def _spmv(A: DeviceMatrix, x: jax.Array) -> jax.Array:
    adt = acc_dtype(x.dtype)
    if _is_matfree(A):
        # matrix-free operator tier (ops.operator): plane values are
        # GENERATED inside the apply -- zero matrix HBM traffic
        return A.matfree_apply(x)
    if isinstance(A, BinnedEllMatrix):
        return _binned_ell_mv(A, x)
    if isinstance(A, DiaMatrix):
        # static shifted views of x; XLA fuses into one VPU loop
        return dia_mv(A.data, A.offsets, A.nrows, x)
    if isinstance(A, EllMatrix):
        # K gathers of n elements each; XLA fuses the multiply-accumulate.
        return jnp.einsum("nk,nk->n", A.data, x[A.cols],
                          preferred_element_type=adt).astype(x.dtype)
    if isinstance(A, CooMatrix):
        prod = A.vals.astype(adt) * x[A.cols].astype(adt)
        return jax.ops.segment_sum(prod, A.rows, num_segments=A.nrows,
                                   indices_are_sorted=True).astype(x.dtype)
    raise TypeError(f"unsupported device matrix {type(A)}")


def matrix_diagonal(A: DeviceMatrix) -> jax.Array:
    """``diag(A)`` as an (nrows,) device array, jit-safe -- the
    preconditioning tier's setup primitive (acg_tpu.precond): extracted
    once per solver, zero host transfers.  Rows without a stored
    diagonal entry (structural padding of the stacked layouts) come
    back exactly 0, which the Jacobi state builder turns into a 0
    inverse (padded residual entries are exactly 0 by construction)."""
    adt = acc_dtype(matrix_dtype(A))
    if _is_matfree(A):
        # the operator-path twin: analytic diagonal through the
        # operator's own hook (typed refusal for user operators
        # registered without one) -- what makes --precond jacobi work
        # matrix-free
        return A.matfree_diagonal().astype(adt)
    if isinstance(A, DiaMatrix):
        if 0 in A.offsets:
            return A.data[A.offsets.index(0)][: A.nrows].astype(adt)
        return jnp.zeros((A.nrows,), dtype=adt)
    if isinstance(A, EllMatrix):
        rows = jnp.arange(A.nrows)[:, None]
        return jnp.sum(jnp.where(A.cols == rows, A.data, 0),
                       axis=1).astype(adt)
    if isinstance(A, CooMatrix):
        on = A.rows == A.cols
        return jax.ops.segment_sum(
            jnp.where(on, A.vals, 0).astype(adt), A.rows,
            num_segments=A.nrows, indices_are_sorted=True)
    if isinstance(A, BinnedEllMatrix):
        d = jnp.zeros((A.nrows,), dtype=adt)
        for rows, data, cols in zip(A.bin_rows, A.bin_data, A.bin_cols):
            contrib = jnp.sum(jnp.where(cols == rows[:, None], data, 0),
                              axis=1).astype(adt)
            d = d.at[rows].add(contrib, unique_indices=True)
        if A.tail_rows.size:
            on = A.tail_rows == A.tail_cols
            d = d + jax.ops.segment_sum(
                jnp.where(on, A.tail_vals, 0).astype(adt), A.tail_rows,
                num_segments=A.nrows, indices_are_sorted=True)
        return d
    raise TypeError(f"unsupported device matrix {type(A)}")


@jax.jit
def _count_nonzero_on_device(arrays):
    """Total nonzeros across a pytree of arrays, as ONE compiled device
    reduction returning a scalar."""
    leaves = jax.tree_util.tree_leaves(arrays)
    return sum(jnp.count_nonzero(a) for a in leaves)


def spmv_flops(A: DeviceMatrix) -> float:
    """Analytic flops per SpMV, reference convention (3 per stored nz).

    nnz is counted ON DEVICE: pulling the planes to the host for a numpy
    count would be an O(matrix) device->host copy -- ~3.8 GB for the
    512^3 DIA planes, i.e. minutes over a tunneled chip, for a flop
    statistic.  Only one scalar crosses the wire here."""
    if _is_matfree(A):
        # analytic count: no planes exist to scan, on device or off
        return 3.0 * float(A.matfree_nnz())
    if isinstance(A, DiaMatrix):
        nnz = float(_count_nonzero_on_device(tuple(A.data)))
    elif isinstance(A, EllMatrix):
        nnz = float(_count_nonzero_on_device((A.data,)))
    elif isinstance(A, BinnedEllMatrix):
        nnz = float(_count_nonzero_on_device(tuple(A.bin_data))
                    + A.tail_vals.size)
    else:
        nnz = float(A.vals.size)
    return 3.0 * nnz
