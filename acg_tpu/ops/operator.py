"""Matrix-free operator tier: ``A`` as an apply, not a stored matrix.

ROADMAP item 5 (arXiv:2205.08909, PAPERS.md): matrix-free high-order
operator application beats assembled SpMV precisely by deleting the
per-iteration A-read from HBM.  The ``gen:`` path already assembles DIA
planes on device (``io.generators.poisson_dia_device``) -- one step
short of never materializing A at all.  This module takes that step:

* :class:`StencilOperator` -- a jit-traversable pytree standing in for
  a :class:`~acg_tpu.ops.spmv.DeviceMatrix` whose SpMV expresses the
  stencil as shifted VIEWS of the reshaped grid (pad + slice + the
  O(grid-side) coefficient tables, fused by XLA into the
  multiply-accumulate) instead of reading O(ndiags * N) planes from
  HBM.  Per-element products are BITWISE IDENTICAL to the assembled
  planes' (constants are exactly representable; variable coefficients
  are pre-rounded host-side in f64 exactly like the assembled ingest)
  and accumulate in the same offset order, so iteration trajectories
  match the assembled-DIA tier bit for bit on the tiers whose applies
  consume loop-carried state -- classic CG (the headline bench
  protocol), s-step, jacobi PCG, batched, and the whole dist tier;
  tiers that CHAIN applies inside one fused region (the pipelined
  setup, cheby's polynomial, the ABFT setup checksum) agree to FMA
  reassociation instead (see ``StencilOperator.matfree_apply``;
  tests/test_matfree.py pins both halves of the contract).
* :class:`UserOperator` + :func:`register_operator` -- the registration
  hook for user-supplied jitted operators: ``apply_fn(captures, x)``
  (and optionally ``diagonal_fn``) registered under a name; the
  operator object itself stays a hashable-meta pytree so it rides the
  solve programs' jit arguments like any device matrix.

Integration is by dispatch, not by new loops: ``ops.spmv.spmv`` (and
``matrix_diagonal`` / ``spmv_flops`` / ``matrix_index_bytes``) recognise
the ``matfree_*`` protocol, so every solver tier -- classic, pipelined,
the PR 12 CA recurrences (``sstep:S`` / ``pipelined:L`` ride
:func:`acg_tpu.recurrence.single_ops`, whose SpMV source this is),
batched multi-RHS, precond (jacobi reads :func:`matrix_diagonal`
through the diagonal hook, cheby needs only applies) and the ABFT
checksum (``c = A^T 1`` computed through the apply at setup) -- inherits
matrix-free operation with zero new recurrence code.  The distributed
restatement (band-partitioned local planes generated per shard, halo
riding the existing exchange machinery) lives in
``parallel.dist.arm_matfree``.

Built-in stencils: constant-coefficient Poisson 1D/2D/3D (the ``gen:``
family) and the variable-coefficient anisotropic 2D family
(``io.generators.aniso_poisson2d_coo`` -- whose coefficients depend
only on the grid row, so three O(n) tables replace O(n^2) planes).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from acg_tpu.errors import AcgError, ErrorCode
from acg_tpu.ops.spmv import acc_dtype


def is_matrix_free(A) -> bool:
    """True for any operator speaking the ``matfree_*`` protocol (the
    dispatch predicate ops.spmv / the solvers / perfmodel share)."""
    return hasattr(A, "matfree_apply")


# -- plane generation ------------------------------------------------------

def stencil_planes(kind: str, grid: tuple, offsets: tuple, tables,
                   nrows: int, dtype, row0=0, nowned=None):
    """The lazily-generated DIA planes of a built-in stencil: one traced
    (nrows,) array per static offset, for global rows ``[row0, row0 +
    nrows)``.  XLA fuses the iota/compare/select chains into the SpMV's
    multiply-accumulate, so no plane ever materialises in HBM.

    Values are bitwise-equal to the assembled ingest's planes: constants
    (Poisson -1 / 2*dim) are exactly representable in every supported
    dtype, and the anisotropic tables arrive pre-rounded from f64
    exactly like ``dia_from_csr``'s ``astype`` (one rounding, host-side,
    in :func:`aniso2d_stencil`).

    ``nowned`` (the distributed local-block mask) zeroes entries whose
    row or column index falls outside ``[0, nowned)`` LOCALLY -- exactly
    the owned x owned split the assembled ``dia_planes_fixed`` stacking
    encodes (out-of-part couplings live in the ghost block, padding rows
    are zero).  ``row0`` may be a traced scalar (per-shard)."""
    n = grid[0]
    glob = (nowned is None and isinstance(row0, int) and row0 == 0)

    def axis_coord(stride: int):
        """The grid coordinate ``(idx // stride) % n`` per row.  The
        global full-grid case builds it as a BROADCAST of a 1-D arange
        over the reshaped ``(-1, n, stride)`` view -- no per-element
        integer division anywhere, which is what makes generating the
        planes cheaper than reading them.  Shard windows (traced row0 /
        owned masks, not grid-aligned) take the iota arithmetic."""
        if glob and nrows % (stride * n) == 0:
            reps = nrows // (stride * n)
            c = jnp.broadcast_to(
                jnp.arange(n, dtype=jnp.int32)[None, :, None],
                (reps, n, stride))
            return c.reshape(nrows)
        idx = jnp.asarray(row0, jnp.int32) + jax.lax.iota(jnp.int32,
                                                          nrows)
        return (idx // stride) % n

    if nowned is not None:
        i_loc = jax.lax.iota(jnp.int32, nrows)
        nown = jnp.asarray(nowned, jnp.int32)

    def local_mask(plane, off):
        if nowned is None:
            return plane
        ok = ((i_loc < nown) & (i_loc + off >= 0) & (i_loc + off < nown))
        return jnp.where(ok, plane, jnp.zeros((), dtype))

    planes = []
    if kind == "poisson":
        _n, dim = grid
        for off in offsets:
            if off == 0:
                plane = jnp.full((nrows,), float(2 * dim), dtype)
            else:
                stride = abs(int(off))
                coord = axis_coord(stride)
                if off < 0:
                    plane = jnp.where(coord > 0, -1.0, 0.0).astype(dtype)
                else:
                    plane = jnp.where(coord < n - 1, -1.0,
                                      0.0).astype(dtype)
            planes.append(local_mask(plane, off))
        return planes
    if kind == "aniso2d":
        wx, wy, dtab = tables

        def row_table(t):
            """``t[j]`` per row: a broadcast over the (n, n) view in
            the global case, a gather on shard windows."""
            if glob and nrows == n * n:
                return jnp.broadcast_to(t[:n, None],
                                        (n, n)).reshape(nrows)
            idx = jnp.asarray(row0, jnp.int32) + jax.lax.iota(
                jnp.int32, nrows)
            return t[idx // n]

        i = axis_coord(1)
        j = axis_coord(n)
        for off in offsets:
            if off == 0:
                plane = row_table(dtab)
            elif off == -1:
                plane = jnp.where(i > 0, -row_table(wx),
                                  jnp.zeros((), dtype))
            elif off == 1:
                plane = jnp.where(i < n - 1, -row_table(wx),
                                  jnp.zeros((), dtype))
            elif off == -n:
                plane = jnp.where(j > 0, -row_table(wy),
                                  jnp.zeros((), dtype))
            elif off == n:
                plane = jnp.where(j < n - 1, -row_table(wy[1:]),
                                  jnp.zeros((), dtype))
            else:
                raise ValueError(f"aniso2d stencil has no offset {off}")
            planes.append(local_mask(plane, off))
        return planes
    raise ValueError(f"unknown stencil kind {kind!r}")


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=["tables"],
                   meta_fields=["kind", "grid", "param", "offsets",
                                "nrows", "ncols_padded", "dtype_name"])
@dataclasses.dataclass
class StencilOperator:
    """A built-in matrix-free stencil, pytree-registered so it rides the
    solve programs' jit arguments exactly like a DeviceMatrix: the O(n)
    coefficient ``tables`` are the only data leaves (empty for
    constant-coefficient stencils), everything else is hashable static
    metadata keying the jit cache."""

    tables: tuple       # () or small rounded coefficient arrays
    kind: str           # "poisson" | "aniso2d"
    grid: tuple         # (n, dim)
    param: float        # aniso stretch eps; 0.0 for constant stencils
    offsets: tuple      # static diagonal offsets, ascending
    nrows: int
    ncols_padded: int
    dtype_name: str     # storage dtype the generated values take

    @property
    def dtype(self):
        return jnp.dtype(self.dtype_name)

    def planes(self, row0=0, nrows: int | None = None, nowned=None):
        return stencil_planes(self.kind, self.grid, self.offsets,
                              self.tables,
                              self.nrows if nrows is None else nrows,
                              self.dtype, row0=row0, nowned=nowned)

    # -- the DeviceMatrix protocol (ops.spmv dispatch) -----------------

    def _shifted(self, x, stride: int, sign: int):
        """``out[idx] = x[idx + sign*stride]`` where the grid neighbour
        exists, else 0 -- a PAD + SLICE on the reshaped
        ``(-1, n, stride)`` view of x: the boundary structure is
        expressed by the array geometry, so no per-element index
        arithmetic exists anywhere in the apply.  This is what makes
        the generated apply CHEAPER than reading planes (the
        plane-generation path must still manufacture an O(N) mask the
        compiler may materialise), not merely traffic-equivalent."""
        n = self.grid[0]
        x3 = x.reshape(-1, n, stride)
        z = jnp.zeros_like(x3[:, :1, :])
        if sign < 0:
            sh = jnp.concatenate([z, x3[:, :-1, :]], axis=1)
        else:
            sh = jnp.concatenate([x3[:, 1:, :], z], axis=1)
        return sh.reshape(x.shape)

    def matfree_apply(self, x):
        """y = A @ x with the stencil structure expressed as shifted
        VIEWS of the reshaped grid: per ascending offset, one
        pad-and-slice neighbour image times its coefficient,
        accumulated in the assembled ``dia_mv``'s offset order with the
        identical per-element products.

        Bitwise contract (tests/test_matfree.py): iteration
        trajectories equal the assembled-DIA tier's bit for bit on the
        tiers whose applies consume/produce loop-carried state --
        classic CG (the headline bench protocol), s-step CG, Jacobi
        PCG, the batched tier, and the whole dist tier (which runs the
        generated-plane form).  Programs that CHAIN applies inside one
        fused region (the pipelined setup's w = A(b - A x0), cheby's
        K-apply polynomial, the ABFT setup) let XLA contract the fused
        multiply-adds differently than the assembled build -- per
        apply the results are still bitwise-equal (verified un-fused),
        in-program they agree to FMA reassociation (~1 ulp/apply) and
        convergence behaviour is identical."""
        adt = acc_dtype(x.dtype)
        n, dim = self.grid
        y = jnp.zeros(x.shape, adt)
        if self.kind == "poisson":
            mone = jnp.asarray(-1.0, adt)
            for off in self.offsets:
                if off == 0:
                    y = y + (jnp.asarray(float(2 * dim), adt)
                             * x.astype(adt))
                else:
                    sh = self._shifted(x, abs(int(off)),
                                       1 if off > 0 else -1)
                    y = y + mone * sh.astype(adt)
            return y.astype(x.dtype)
        # aniso2d: coefficients depend only on the grid row j, so each
        # offset is one broadcast of an O(n) table over the (n, n) view
        wx, wy, dtab = self.tables
        x2 = x.reshape(n, n)
        y2 = y.reshape(n, n)
        for off in self.offsets:
            if off == 0:
                y2 = y2 + dtab[:, None].astype(adt) * x2.astype(adt)
                continue
            stride = abs(int(off))
            sh = self._shifted(x, stride,
                               1 if off > 0 else -1).reshape(n, n)
            if stride == 1:
                coeff = -wx[:, None].astype(adt)
            elif off < 0:
                coeff = -wy[:-1, None].astype(adt)     # -wy[j]
            else:
                coeff = -wy[1:, None].astype(adt)      # -wy[j+1]
            y2 = y2 + coeff * sh.astype(adt)
        return y2.reshape(x.shape).astype(x.dtype)

    def matfree_apply_multi(self, X):
        """Multi-column twin (the batched tier): the shifted-view apply
        vmapped over the batch axis -- same per-column accumulation as
        the assembled multi-vector SpMV."""
        return jax.vmap(self.matfree_apply, in_axes=1, out_axes=1)(X)

    def matfree_diagonal(self):
        """Analytic ``diag(A)`` (the ``--precond jacobi`` twin of
        ``ops.spmv.matrix_diagonal``), in the accumulation dtype like
        the assembled extraction."""
        d = self.planes()[self.offsets.index(0)]
        return d.astype(acc_dtype(self.dtype))

    def matfree_nnz(self) -> float:
        """Analytic stored-nonzero count (the assembled twin's nnz):
        each off-diagonal plane is zero on one boundary slice of
        N/n entries."""
        n, dim = self.grid
        N = self.nrows
        return float((2 * dim + 1) * N - 2 * dim * (N // n))

    def table_bytes(self) -> int:
        """HBM bytes the generated planes actually read per apply (the
        O(n) coefficient tables; 0 for constant stencils) -- the
        matrix-bytes term the --explain roofline prices instead of
        nnz * itemsize."""
        return sum(int(np.prod(np.shape(t))) * self.dtype.itemsize
                   for t in self.tables)

    # -- host twins (dist setup / oracles) -----------------------------

    def host_diagonal(self) -> np.ndarray:
        """diag(A) as host numpy f64 OF THE ROUNDED stored values --
        what the stacked Jacobi builder inverts (matching the device
        extraction exactly)."""
        n, dim = self.grid
        if self.kind == "poisson":
            return np.full(self.nrows, float(2 * dim))
        dtab = np.asarray(self.tables[2], np.float64)
        return np.repeat(dtab, n)

    def identity(self) -> str:
        """The operator's provenance string (stats manifest, bench case
        keys: perfmodel._operator_keyed)."""
        n, dim = self.grid
        if self.kind == "poisson":
            return f"stencil:poisson{dim}d:{n}"
        return f"stencil:aniso2d:{n}:{self.param:g}"


def poisson_stencil(n: int, dim: int, dtype=jnp.float32) -> StencilOperator:
    """Constant-coefficient Poisson stencil operator (1D/2D/3D), the
    matrix-free twin of ``io.generators.poisson_dia`` /
    ``poisson_dia_device`` (same offsets, same values -- bitwise)."""
    if dim not in (1, 2, 3):
        raise ValueError(f"poisson stencil dim must be 1, 2 or 3 "
                         f"(got {dim})")
    if n < 2:
        raise ValueError(f"poisson stencil needs n >= 2 (got {n})")
    N = n ** dim
    offsets = sorted([s for a in range(dim)
                      for s in (-(n ** a), n ** a)] + [0])
    return StencilOperator(tables=(), kind="poisson", grid=(n, dim),
                           param=0.0,
                           offsets=tuple(int(o) for o in offsets),
                           nrows=N, ncols_padded=N,
                           dtype_name=str(jnp.dtype(dtype)))


def aniso2d_stencil(n: int, eps: float,
                    dtype=jnp.float32) -> StencilOperator:
    """The variable-coefficient anisotropic 2D family
    (``io.generators.aniso_poisson2d_coo``) as a matrix-free operator:
    the edge weights depend only on the grid row, so THREE O(n) tables
    (x-edge weights, y-edge weights, and the PRE-SUMMED diagonal)
    replace the O(n^2) planes.  Tables are computed in f64 and rounded
    ONCE to the storage dtype -- the same single rounding the assembled
    ingest applies (f64 COO -> ``astype(dtype)`` planes), which is what
    makes the generated values bitwise-equal to the assembled ones
    (summing pre-rounded weights on device would round differently)."""
    if not 0.0 < eps <= 1.0:
        raise ValueError(f"aniso stretch factor must be in (0, 1], "
                         f"got {eps}")
    j = np.arange(n)
    wx = eps ** ((j + 0.5) / n)                    # f64, like the gen
    e = np.arange(n + 1)
    wy = eps ** (-(e / n))
    dtab = 2 * wx + wy[:-1] + wy[1:]               # f64 sum, THEN round
    npdt = np.dtype(str(jnp.dtype(dtype)))
    tables = (jnp.asarray(wx.astype(npdt)), jnp.asarray(wy.astype(npdt)),
              jnp.asarray(dtab.astype(npdt)))
    N = n * n
    return StencilOperator(tables=tables, kind="aniso2d", grid=(n, 2),
                           param=float(eps),
                           offsets=(-n, -1, 0, 1, n),
                           nrows=N, ncols_padded=N,
                           dtype_name=str(jnp.dtype(dtype)))


# -- user-supplied operators (the registration hook) ----------------------

_USER_OPS: dict = {}


def register_operator(name: str, apply_fn, diagonal_fn=None,
                      nnz: float | None = None) -> None:
    """Register a user-supplied jitted operator under ``name``:
    ``apply_fn(captures, x) -> y`` is traced into every solve program
    exactly where the assembled SpMV would run (``captures`` is the
    operator instance's pytree-leaf tuple); ``diagonal_fn(captures) ->
    diag`` arms ``--precond jacobi`` (absent: jacobi refuses
    self-describingly); ``nnz`` feeds the flop statistic (default: 0,
    reported as unknown work)."""
    if not callable(apply_fn):
        raise ValueError(f"operator {name!r}: apply_fn must be callable")
    _USER_OPS[str(name)] = {"apply": apply_fn, "diagonal": diagonal_fn,
                            "nnz": nnz}


def registered_operators() -> tuple:
    return tuple(sorted(_USER_OPS))


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=["captures"],
                   meta_fields=["name", "nrows", "ncols_padded",
                                "dtype_name"])
@dataclasses.dataclass
class UserOperator:
    """A registered user operator as a solve-program argument: the
    closed-over arrays ride ``captures`` (data leaves), the registry
    ``name`` selects the apply at trace time."""

    captures: tuple
    name: str
    nrows: int
    ncols_padded: int
    dtype_name: str

    @property
    def dtype(self):
        return jnp.dtype(self.dtype_name)

    def _entry(self):
        try:
            return _USER_OPS[self.name]
        except KeyError:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"operator {self.name!r} is not registered in this "
                f"process (register_operator must run before the solve)")

    def matfree_apply(self, x):
        return self._entry()["apply"](self.captures, x)

    def matfree_diagonal(self):
        dfn = self._entry()["diagonal"]
        if dfn is None:
            raise AcgError(
                ErrorCode.NOT_SUPPORTED,
                f"operator {self.name!r} was registered without a "
                f"diagonal_fn: --precond jacobi needs the analytic "
                f"diagonal (register_operator(..., diagonal_fn=...), "
                f"or use --precond cheby:K, which needs only applies)")
        return dfn(self.captures)

    def matfree_nnz(self) -> float:
        return float(self._entry()["nnz"] or 0.0)

    def table_bytes(self) -> int:
        return sum(int(np.prod(np.shape(t))) * np.dtype(
            getattr(t, "dtype", np.float64)).itemsize
            for t in jax.tree_util.tree_leaves(self.captures))

    def identity(self) -> str:
        return f"user:{self.name}"


def user_operator(name: str, nrows: int, dtype=jnp.float32,
                  captures: tuple = ()) -> UserOperator:
    """Instantiate a registered operator for an ``nrows``-row system."""
    if str(name) not in _USER_OPS:
        raise AcgError(
            ErrorCode.INVALID_VALUE,
            f"operator {name!r} is not registered "
            f"(known: {', '.join(registered_operators()) or 'none'}); "
            f"call acg_tpu.ops.operator.register_operator first")
    return UserOperator(captures=tuple(captures), name=str(name),
                        nrows=int(nrows), ncols_padded=int(nrows),
                        dtype_name=str(jnp.dtype(dtype)))


# -- CLI spec parsing ------------------------------------------------------

def _gen_desc(gen) -> str:
    """Human spelling of a parsed gen: matrix spec for refusals."""
    kind, dim, n = gen[0], gen[1], gen[2]
    if kind == "poisson":
        return f"gen:poisson{dim}d:{n}"
    return f"gen:{kind}:{n}"


def parse_operator_spec(text):
    """``--operator`` grammar -> spec tuple (None = disarmed):

    * ``none``/empty             -> None (byte-identical assembled path)
    * ``stencil``                -> ("auto",): derive the stencil from
                                   the ``gen:`` matrix spec (+ --aniso)
    * ``stencil:poisson1d:N`` (2d/3d) -> ("poisson", dim, N)
    * ``stencil:aniso2d:N:EPS``  -> ("aniso2d", N, EPS)
    * ``user:NAME``              -> ("user", NAME)
    """
    if text is None:
        return None
    t = str(text).strip()
    if t in ("", "none"):
        return None
    if t == "stencil":
        return ("auto",)
    fields = t.split(":")
    if fields[0] == "user":
        if len(fields) != 2 or not fields[1]:
            raise ValueError(f"operator spec {text!r}: expected "
                             f"user:NAME")
        return ("user", fields[1])
    if fields[0] != "stencil":
        raise ValueError(
            f"operator spec {text!r}: expected none, stencil, "
            f"stencil:poisson1d|poisson2d|poisson3d:N, "
            f"stencil:aniso2d:N:EPS, or user:NAME")
    kind = fields[1] if len(fields) > 1 else ""
    try:
        if kind in ("poisson1d", "poisson2d", "poisson3d"):
            if len(fields) != 3:
                raise ValueError
            dim = int(kind[7])
            n = int(fields[2])
            if n < 2:
                raise ValueError
            return ("poisson", dim, n)
        if kind == "aniso2d":
            if len(fields) != 4:
                raise ValueError
            n = int(fields[2])
            eps = float(fields[3])
            if n < 2 or not 0.0 < eps <= 1.0:
                raise ValueError
            return ("aniso2d", n, eps)
    except ValueError:
        pass
    raise ValueError(
        f"operator spec {text!r}: expected none, stencil, "
        f"stencil:poisson1d|poisson2d|poisson3d:N, "
        f"stencil:aniso2d:N:EPS, or user:NAME")


def build_operator(spec, dtype, gen=None, aniso=None, nrows=None):
    """Spec tuple -> operator instance.  ``gen`` is the parsed ``gen:``
    matrix spec tuple (kind, dim, n, N, avg) when the matrix came from a
    generator -- the ``("auto",)`` spelling derives the stencil from it,
    and explicit spellings are validated against it (an operator that
    does not compute the matrix being solved would silently answer a
    different system)."""
    if spec is None:
        return None
    if spec[0] == "auto":
        if gen is None or gen[0] != "poisson":
            raise ValueError(
                "--operator stencil derives the stencil from a "
                "gen:poisson* matrix spec (files and gen:irregular are "
                "assembled by definition); name the stencil explicitly "
                "(stencil:poisson2d:N, stencil:aniso2d:N:EPS) or use a "
                "registered user:NAME operator")
        _, dim, n, _N, _ = gen
        if aniso is not None:
            return aniso2d_stencil(n, float(aniso), dtype=dtype)
        return poisson_stencil(n, dim, dtype=dtype)
    if spec[0] == "poisson":
        _, dim, n = spec
        # the gen: matrix must AFFIRMATIVELY match: a non-matching kind
        # (irregular, wrong dim/n) or an --aniso selection means the
        # stencil would silently compute a different system than the
        # matrix being solved
        if gen is not None and (gen[0] != "poisson"
                                or (gen[1], gen[2]) != (dim, n)):
            raise ValueError(
                f"--operator stencil:poisson{dim}d:{n} does not compute "
                f"the gen: matrix being solved ({_gen_desc(gen)})")
        if aniso is not None:
            raise ValueError(
                "--aniso selects the variable-coefficient family; use "
                "--operator stencil (auto) or stencil:aniso2d:N:EPS")
        return poisson_stencil(n, dim, dtype=dtype)
    if spec[0] == "aniso2d":
        _, n, eps = spec
        if gen is not None and (gen[0] != "poisson" or gen[1] != 2
                                or gen[2] != n):
            raise ValueError(
                f"--operator stencil:aniso2d:{n}:{eps:g} does not "
                f"compute the gen: matrix being solved "
                f"({_gen_desc(gen)})")
        if gen is not None and aniso is None:
            # without --aniso the gen matrix IS the constant-coefficient
            # family -- the aniso stencil would silently solve the
            # stretched-grid system instead
            raise ValueError(
                f"--operator stencil:aniso2d:{n}:{eps:g} computes the "
                f"anisotropic family, but the matrix being solved is "
                f"the constant-coefficient gen:poisson2d:{n} (add "
                f"--aniso {eps:g} to solve the anisotropic system)")
        if aniso is not None and float(aniso) != float(eps):
            raise ValueError(
                f"--operator stencil:aniso2d:{n}:{eps:g} disagrees "
                f"with --aniso {aniso:g}")
        return aniso2d_stencil(n, eps, dtype=dtype)
    if spec[0] == "user":
        if nrows is None:
            raise ValueError("user operators need the system size")
        return user_operator(spec[1], nrows, dtype=dtype)
    raise ValueError(f"unknown operator spec {spec!r}")


def operator_identity(A) -> str | None:
    """Provenance string of a matrix-free operator (None for assembled
    matrices) -- joins the stats manifest and the bench case key."""
    if is_matrix_free(A) and hasattr(A, "identity"):
        return A.identity()
    return None
