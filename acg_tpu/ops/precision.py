"""Extended-precision building blocks for f32-native TPU solves.

The reference is strictly FP64 (``ACG_DOUBLE`` is its only dtype,
``comm.h:180-183``); TPU f64 is software-emulated and slow.  This module
supplies the standard mitigations (SURVEY.md section 7 "hard parts"):

* **Error-free transforms** (two_sum / split / two_prod, Dekker/Knuth):
  exact f32 sum and product representations as (hi, lo) pairs, entirely
  in hardware f32 ops, jit- and vmap-safe.
* **Compensated reductions**: `df_sum` tree-reduces an array in
  double-float ("df64") arithmetic -- ~2x f32 precision (~48-bit
  mantissa) at a small constant factor over a plain `jnp.sum`;
  `dot_compensated` is the Ogita-Rump-Oishi dot2 built on it.  Used for
  the CG scalars (gamma, (p,t)) whose f32 rounding is what stalls plain
  f32 CG near 1e-6 relative residuals.
* **Iterative refinement** lives in
  :class:`acg_tpu.solvers.refine.RefinedSolver`: f64 outer residual on
  host, f32 inner CG on device -- f64-quality solutions at f32 device
  speed (Wilkinson; the standard mixed-precision linear-solver loop).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def two_sum(a, b):
    """Knuth two-sum: s + e == a + b exactly (|e| <= ulp(s)/2)."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


_MANTISSA_BITS = {"float32": 24, "float64": 53, "bfloat16": 8,
                  "float16": 11}


def split(a):
    """Dekker split of a float into hi + lo with non-overlapping
    half-width mantissas (12+12 bits for f32, 27+26 for f64); the split
    constant is derived from the input dtype."""
    bits = _MANTISSA_BITS[jnp.dtype(a.dtype).name]
    c = jnp.asarray(2.0 ** ((bits + 1) // 2) + 1.0, a.dtype) * a
    hi = c - (c - a)
    return hi, a - hi


def two_prod(a, b):
    """Dekker two-product: p + e == a * b exactly (no FMA needed)."""
    p = a * b
    ah, al = split(a)
    bh, bl = split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


def df_add(x, y):
    """Double-float addition: (hi, lo) + (hi, lo) -> (hi, lo)."""
    xh, xl = x
    yh, yl = y
    s, e = two_sum(xh, yh)
    e = e + xl + yl
    hi, lo = two_sum(s, e)
    return hi, lo


def df_sum(hi: jax.Array, lo: jax.Array | None = None):
    """Tree-sum an array in double-float arithmetic.

    Folds halves with `df_add` (log2(n) vectorised passes, ~2n df-adds
    total), so the reduction itself carries ~48 bits -- unlike a plain
    f32 tree sum whose error grows with log(n) ulps.  Returns (hi, lo)
    scalars.
    """
    if lo is None:
        lo = jnp.zeros_like(hi)
    n = hi.shape[0]
    # pad to a power of two (zeros are exact in df arithmetic)
    p2 = 1 << max(0, (n - 1).bit_length())
    if p2 != n:
        hi = jnp.pad(hi, (0, p2 - n))
        lo = jnp.pad(lo, (0, p2 - n))
    while p2 > 1:
        half = p2 // 2
        hi, lo = df_add((hi[:half], lo[:half]), (hi[half:], lo[half:]))
        p2 = half
    return hi[0], lo[0]


def dot_compensated(x: jax.Array, y: jax.Array):
    """Ogita-Rump-Oishi dot2: the dot product with ~2x working
    precision.  Returns (hi, lo); ``hi + lo`` is the compensated value.

    The role of the reference's f64 cublasDdot for the CG scalars
    (``cgcuda.c:913-972``) when vectors are stored in f32.
    """
    p, e = two_prod(x, y)
    return df_sum(p, e)


def dot2(x: jax.Array, y: jax.Array) -> jax.Array:
    """Compensated dot product collapsed to a single working-precision
    scalar (the 'almost-f64 then round' value)."""
    hi, lo = dot_compensated(x, y)
    return hi + lo
