"""Hand-written Pallas TPU kernels for the CG hot loop.

The reference's device-kernel tier (``acg/cg-kernels-cuda.cu``): merge-CSR
SpMV (``:340-441``), fused BLAS-1 with device scalars (``:78-303``), and
the 6-vector pipelined update (``:187-269``).  On TPU the XLA compiler
already fuses elementwise chains well, so each kernel here exists to beat
a *specific* HBM-traffic bound the fusion cannot reach:

* :func:`dia_spmv` -- DIA SpMV with a single pass over ``x``: the XLA
  formulation (``ops/spmv.py:dia_mv``) reads one shifted copy of ``x``
  per diagonal (D+1 vector reads + 1 write for D diagonals); this kernel
  DMAs each x tile (plus band halo) into VMEM once and applies all D
  statically-shifted multiplies from VMEM, for D/2+2-ish units of HBM
  traffic -- the same traffic argument as the reference's merge-CSR
  kernel, restated for a vector architecture.
* :func:`fused_pipelined_update` -- the Ghysels-Vanroose 6-vector update
  (z,t,p,x,r,w) in one pass with alpha/beta in SMEM, the analog of
  ``acgsolvercuda_pipelined_update_kernel`` (``cg-kernels-cuda.cu:
  187-269``).

Both run in interpret mode on CPU (tests) and compiled on TPU.  Whether
they actually beat XLA fusion is *measured* (``scripts/bench_pallas.py``,
BASELINE.md) -- the solvers select per measurement via
``kernels="pallas"``.

The DISTRIBUTED fused tier (``kernels='fused'`` with ``--nparts``) does
not use these single-device kernels: it is the recurrence builder's
emission over the interior|border OVERLAPPED SpMV
(``parallel.dist.make_dist_spmv_overlapped`` -- one-sided halo DMA in
flight behind the interior rows' work).  Folding that tier's axpy/dot
updates into true per-iteration Pallas mega-kernels on the split row
sets is the remaining rung of ROADMAP item 4; the ``_window_copies``
machinery here is the intended substrate.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from acg_tpu.ops.spmv import acc_dtype

# row-tile length for the SpMV kernel; multiple of the f32 (8,128) tile
TILE = 16384
LANE = 128


def _pad_to(x, m):
    r = (-x.shape[0]) % m
    return jnp.pad(x, (0, r)) if r else x


@functools.partial(jax.jit, static_argnames=("offsets", "interpret"))
def dia_spmv(planes, offsets: tuple, x, interpret: bool = False):
    """y = A @ x for DIA ``planes`` (tuple of (n,) arrays, one per static
    diagonal offset), reading ``x`` through VMEM once per row tile.

    Equivalent to :func:`acg_tpu.ops.spmv.dia_mv` with x-length == n
    (square blocks); see that function for the semantics.

    Fast path (n divisible by the row tile, band within one tile): each
    tile issues three static-size DMAs straight out of x -- body, left
    halo, right halo -- with edge tiles zero-filling the out-of-range
    halo instead of reading it, so no padded copy of x is ever
    materialised.  Out-of-range x positions only ever multiply plane
    entries that are structurally zero (no matrix entry has a column off
    the end), so the zero fill is correctness-neutral; it exists to keep
    NaN-free garbage out of uninitialised VMEM.  Ragged shapes take a
    jnp.pad fallback.
    """
    n = x.shape[0]
    route = dia_spmv_route(offsets, n, x.dtype, ndiags=len(planes))
    if route[0] == "fast":
        # the fast path IS the clustered kernel with no far windows
        _, Lpad, Rpad, tile, align = route
        return _dia_spmv_clustered(planes, offsets, x, tuple(offsets), (),
                                   Lpad, Rpad, tile, align, interpret)
    if route[0] == "clustered":
        _, central, far, Lpad, Rpad, tile, align = route
        return _dia_spmv_clustered(planes, offsets, x, central, far,
                                   Lpad, Rpad, tile, align, interpret)
    if route[0] == "xla":
        from acg_tpu.ops.spmv import dia_mv

        return dia_mv(planes, offsets, n, x)
    L = max(0, -min(offsets))
    R = max(0, max(offsets))
    return _dia_spmv_padded(planes, offsets, x, L, R, interpret)


@functools.partial(jax.jit, static_argnames=("offsets", "interpret"))
def dia_spmv_dot(planes, offsets: tuple, x, interpret: bool = False):
    """``(y, dot(x, y))`` with the dot fused into the SpMV pass.

    The classic CG step needs ``t = A p`` immediately followed by
    ``(p, t)`` (``cgcuda.c:913``: cusparseSpMV then cublasDdot).  Fusing
    the scalar into the kernel saves the dot's two full vector re-reads
    (~13%% of the iteration's HBM traffic on the flagship).  Falls back
    to kernel-then-``jnp.dot`` on routes without the fused variant.
    """
    n = x.shape[0]
    route = dia_spmv_route(offsets, n, x.dtype, ndiags=len(planes))
    if route[0] == "fast":
        _, Lpad, Rpad, tile, align = route
        y, d = _dia_spmv_clustered(planes, offsets, x, tuple(offsets), (),
                                   Lpad, Rpad, tile, align, interpret,
                                   with_dot=True)
        return y, d[0].astype(x.dtype)
    if route[0] == "clustered":
        _, central, far, Lpad, Rpad, tile, align = route
        y, d = _dia_spmv_clustered(planes, offsets, x, central, far,
                                   Lpad, Rpad, tile, align, interpret,
                                   with_dot=True)
        return y, d[0].astype(x.dtype)
    y = dia_spmv(planes, offsets, x, interpret=interpret)
    return y, jnp.dot(x, y)


def dia_spmv_route(offsets: tuple, n: int, dtype, ndiags: int | None = None):
    """Which implementation :func:`dia_spmv` will take for this shape:
    ``("fast", Lpad, Rpad, tile, align)`` (single-window kernel),
    ``("clustered", central, far, Lpad, Rpad, tile, align)``
    (multi-window kernel for clustered diagonals), ``("padded",)``, or
    ``("xla",)``.  Exposed so callers reporting a kernel tier (bench)
    can record what actually ran instead of what was requested."""
    ndiags = len(offsets) if ndiags is None else ndiags
    L = max(0, -min(offsets))
    R = max(0, max(offsets))
    itemsize = jnp.dtype(dtype).itemsize
    # scoped-VMEM budget per grid step: the x window plus the
    # double-buffered BlockSpec tiles (D planes + y), under the ~16 MB
    # scoped limit with margin.  A band too wide for this budget has no
    # x-reuse win anyway (each tile's window would mostly be halo), so
    # those matrices go to XLA's shifted-views formulation instead.
    budget = 12 * 2 ** 20

    def vmem_bytes(tile, halo):
        return (tile + 2 * halo + 2 * (ndiags + 1) * tile) * itemsize

    # Mosaic must prove DMA slice offsets divisible by the flattened
    # (sublane x lane) tile; round the halo sizes up to that quantum so
    # every HBM/VMEM DMA offset is a multiple of it
    align = {4: 1024, 2: 2048}.get(itemsize)
    if align is not None:
        Lpad = L + (-L) % align
        Rpad = R + (-R) % align
        band = max(Lpad, Rpad)
        tile = TILE
        while tile < band and vmem_bytes(2 * tile, band) <= budget:
            tile *= 2
        if (band <= tile and n % tile == 0 and n >= tile
                and vmem_bytes(tile, band) <= budget):
            return ("fast", Lpad, Rpad, tile, align)
        clustered = _cluster_route(offsets, n, itemsize, align, budget,
                                   ndiags)
        if clustered is not None:
            return clustered
    if L + R >= TILE:
        # wide band: the window is mostly halo, so the single-x-pass
        # traffic argument is void -- D+1 passes from XLA win
        return ("xla",)
    return ("padded",)


def _cluster_route(offsets, n, itemsize, align, budget, ndiags):
    """Multi-window variant for stencils whose diagonals CLUSTER (3D
    Poisson: {-n^2}, {-n..n}, {+n^2}): one VMEM window per cluster
    keeps the single-x-pass traffic argument even when the full band is
    far too wide for one window.  Far clusters must be single offsets on
    tile boundaries (their window is then exactly the x tile shifted by
    whole tiles, so edge handling is a static in-range predicate);
    the cluster containing 0 is handled like the fast path."""
    if n % TILE or n < TILE:
        return None
    sorted_offs = sorted(offsets)
    clusters: list[list[int]] = [[sorted_offs[0]]]
    for o in sorted_offs[1:]:
        if o - clusters[-1][-1] > TILE // 2:
            clusters.append([o])
        else:
            clusters[-1].append(o)
    if len(clusters) < 2:
        return None
    central = min(clusters, key=lambda c: min(abs(o) for o in c))
    far = [c for c in clusters if c is not central]
    if any(len(c) != 1 or c[0] % TILE or abs(c[0]) >= n for c in far):
        return None
    L = max(0, -min(central))
    R = max(0, max(central))
    Lpad = L + (-L) % align
    Rpad = R + (-R) % align
    if max(Lpad, Rpad) > TILE:
        return None

    def vmem(tile):
        return (tile + Lpad + Rpad + len(far) * tile
                + 2 * (ndiags + 1) * tile) * itemsize

    # grow the tile while the far offsets stay tile-multiples and VMEM
    # fits: fewer grid steps amortise the per-step DMA round-trips
    # (8192 steps of overhead measurably beat the traffic saving at
    # 512^3 with the base tile)
    tile = TILE
    while (n % (2 * tile) == 0 and vmem(2 * tile) <= budget
           and all(c[0] % (2 * tile) == 0 for c in far)):
        tile *= 2
    if vmem(tile) > budget:
        return None
    return ("clustered", tuple(central), tuple(c[0] for c in far),
            Lpad, Rpad, tile, align)


def _window_copies(hbm, wref, sems, s0: int, i, grid: int, tile: int,
                   Lpad: int, Rpad: int, align: int, dtype):
    """(start, wait) callables streaming HBM tile ``i`` plus its left/
    right band halos into a ``(Lpad + tile + Rpad,)`` VMEM window, edge
    tiles zero-filling the out-of-range halo (correctness-neutral: those
    positions only multiply structural zeros).  Uses semaphores
    ``sems[s0:s0+3]``.  Shared by the single-x-pass SpMV kernels and the
    fused CG phase A, so the subtle Mosaic DMA logic (alignment proofs,
    edge fills) lives once."""
    # int32-explicit semaphore indices: under jax_enable_x64 a Python
    # int traces as an i64 constant, which tpu.memref_slice rejects
    sem = [jnp.int32(s0 + k) for k in range(3)]
    body_cp = pltpu.make_async_copy(
        hbm.at[pl.ds(pl.multiple_of(i * tile, align), tile)],
        wref.at[pl.ds(Lpad, tile)], sems.at[sem[0]])

    def _left_cp():
        return pltpu.make_async_copy(
            hbm.at[pl.ds(pl.multiple_of(i * tile - Lpad, align), Lpad)],
            wref.at[pl.ds(0, Lpad)], sems.at[sem[1]])

    def _right_cp():
        return pltpu.make_async_copy(
            hbm.at[pl.ds(pl.multiple_of((i + 1) * tile, align), Rpad)],
            wref.at[pl.ds(Lpad + tile, Rpad)], sems.at[sem[2]])

    def start():
        body_cp.start()
        if Lpad:
            @pl.when(i > 0)
            def _():
                _left_cp().start()

            @pl.when(i == 0)
            def _():
                wref[pl.ds(0, Lpad)] = jnp.zeros((Lpad,), dtype)
        if Rpad:
            @pl.when(i < grid - 1)
            def _():
                _right_cp().start()

            @pl.when(i == grid - 1)
            def _():
                wref[pl.ds(Lpad + tile, Rpad)] = jnp.zeros((Rpad,), dtype)

    def wait():
        if Lpad:
            @pl.when(i > 0)
            def _():
                _left_cp().wait()
        if Rpad:
            @pl.when(i < grid - 1)
            def _():
                _right_cp().wait()
        body_cp.wait()

    return start, wait


def _dia_spmv_clustered(planes, offsets, x, central, far, Lpad, Rpad,
                        tile, align, interpret, with_dot=False):
    """Multi-window single-x-pass SpMV (see ``_cluster_route``): the
    central cluster reads body + left/right halos (the single-window
    "fast" route is this kernel with ``far=()``); each far
    offset reads exactly one whole x tile shifted by ``offset/tile``
    tiles (zero-filled when that tile is off either end).

    ``with_dot=True`` additionally returns ``dot(x, y)`` accumulated in
    SMEM across the (sequential) grid -- the CG step's (p, Ap) scalar
    for free, saving the separate dot's two full vector re-reads."""
    n = x.shape[0]
    grid = n // tile
    win = tile + Lpad + Rpad
    shifts = [o // tile for o in far]
    # plane order: kernel args follow `planes`/`offsets` order; map each
    # offset to (central?, window index)
    central_set = set(central)

    def kernel(x_hbm, *plane_refs_and_out):
        nout = 2 if with_dot else 1
        plane_refs = plane_refs_and_out[:-nout]
        y_ref = plane_refs_and_out[-nout]
        dot_ref = plane_refs_and_out[-1] if with_dot else None
        i = pl.program_id(0)

        def body(xwin, *fwins_and_sems):
            fwins = fwins_and_sems[:-1]
            sems = fwins_and_sems[-1]
            # start every copy first, wait after: the DMAs overlap each
            # other (and the zero-fills) instead of serialising the
            # grid step on round-trips
            start, wait = _window_copies(x_hbm, xwin, sems, 0, i, grid,
                                         tile, Lpad, Rpad, align, x.dtype)
            start()
            for f, (fwin, s) in enumerate(zip(fwins, shifts)):
                src = i + s  # whole-tile shift: static in-range test

                @pl.when((src >= 0) & (src < grid))
                def _(fwin=fwin, src=src, f=f):
                    pltpu.make_async_copy(
                        x_hbm.at[pl.ds(
                            pl.multiple_of(src * tile, align), tile)],
                        fwin, sems.at[jnp.int32(3 + f)]).start()

                @pl.when((src < 0) | (src >= grid))
                def _(fwin=fwin):
                    fwin[...] = jnp.zeros((tile,), x.dtype)
            for f, (fwin, s) in enumerate(zip(fwins, shifts)):
                src = i + s

                @pl.when((src >= 0) & (src < grid))
                def _(fwin=fwin, src=src, f=f):
                    pltpu.make_async_copy(
                        x_hbm.at[pl.ds(
                            pl.multiple_of(src * tile, align), tile)],
                        fwin, sems.at[jnp.int32(3 + f)]).wait()
            wait()
            # sub-f32 storage accumulates in f32: the converts are free
            # on the VPU, VMEM/HBM stay half-width
            kadt = acc_dtype(x.dtype)
            acc = jnp.zeros((tile,), kadt)
            far_idx = {o: f for f, o in enumerate(far)}
            for pr, off in zip(plane_refs, offsets):
                if off in central_set:
                    acc = acc + (pr[:].astype(kadt)
                                 * xwin[pl.ds(Lpad + off, tile)].astype(kadt))
                else:
                    acc = acc + (pr[:].astype(kadt)
                                 * fwins[far_idx[off]][:].astype(kadt))
            y_ref[:] = acc.astype(x.dtype)
            if with_dot:
                # TPU grids run sequentially, so accumulating the
                # partial into the (1,)-SMEM output across steps is
                # safe; products are widened to the accumulation dtype
                # before the reduction so bf16 inputs don't collapse
                # the scalar
                adt = acc_dtype(x.dtype)
                partial = jnp.sum(acc.astype(adt)
                                  * xwin[pl.ds(Lpad, tile)].astype(adt))

                @pl.when(i == 0)
                def _():
                    dot_ref[0] = partial

                @pl.when(i > 0)
                def _():
                    dot_ref[0] += partial

        pl.run_scoped(body, pltpu.VMEM((win,), x.dtype),
                      *[pltpu.VMEM((tile,), x.dtype) for _ in far],
                      pltpu.SemaphoreType.DMA((3 + len(far),)))

    tile_spec = pl.BlockSpec((tile,), lambda i: (i,),
                             memory_space=pltpu.VMEM)
    out_specs = tile_spec
    out_shape = jax.ShapeDtypeStruct((n,), x.dtype)
    if with_dot:
        out_specs = (tile_spec,
                     pl.BlockSpec((1,), lambda i: (0,),
                                  memory_space=pltpu.SMEM))
        out_shape = (out_shape, jax.ShapeDtypeStruct((1,), acc_dtype(x.dtype)))
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] + [
            pl.BlockSpec((tile,), lambda i: (i,), memory_space=pltpu.VMEM)
            for _ in planes],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(x, *planes)


def _dia_spmv_padded(planes, offsets, x, L, R, interpret):
    """Ragged-shape fallback: one padded x copy, one DMA per tile."""
    n = x.shape[0]
    tile = TILE if n >= TILE else (n + (-n) % LANE)
    planes = tuple(_pad_to(p, tile) for p in planes)
    npad = planes[0].shape[0]
    grid = npad // tile
    win = tile + L + R
    win = win + (-win) % 4096  # DMA-offset alignment, any dtype
    # sized so the last tile's window slice stays in range
    xp = jnp.pad(x, (L, (grid - 1) * tile + win - L - n))

    def kernel(xp_ref, *plane_refs_and_out):
        plane_refs = plane_refs_and_out[:-1]
        y_ref = plane_refs_and_out[-1]
        i = pl.program_id(0)

        def body(xwin, sem):
            cp = pltpu.make_async_copy(
                xp_ref.at[pl.ds(i * tile, win)], xwin, sem)
            cp.start()
            cp.wait()
            kadt = acc_dtype(x.dtype)
            acc = jnp.zeros((tile,), kadt)
            for pr, off in zip(plane_refs, offsets):
                acc = acc + (pr[:].astype(kadt)
                             * xwin[pl.ds(L + off, tile)].astype(kadt))
            y_ref[:] = acc.astype(x.dtype)

        pl.run_scoped(body, pltpu.VMEM((win,), x.dtype),
                      pltpu.SemaphoreType.DMA)

    y = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] + [
            pl.BlockSpec((tile,), lambda i: (i,), memory_space=pltpu.VMEM)
            for _ in planes],
        out_specs=pl.BlockSpec((tile,), lambda i: (i,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((npad,), x.dtype),
        interpret=interpret,
    )(xp, *planes)
    return y[:n]


def fused_cg_route(offsets: tuple, n: int, dtype) -> tuple | None:
    """(Lpad, Rpad, tile, align) when the two-phase fused CG iteration
    supports this shape (square DIA, single-window band, n divisible by
    the tile), else None.

    The tile is grown beyond the SpMV route's choice while VMEM allows:
    even with the cross-step double-buffered windows, fewer/larger
    steps amortise the per-step fixed costs (slot bookkeeping, output
    tile turnover); the pre-double-buffering measurement (base tile
    losing ~30% to synchronous DMAs) established the direction and the
    growth stays beneficial-or-neutral after it."""
    route = dia_spmv_route(offsets, n, dtype)
    if route[0] != "fast":
        return None
    Lpad, Rpad, tile, align = route[1:]
    ndiags = len(offsets)
    itemsize = jnp.dtype(dtype).itemsize
    budget = 12 * 2 ** 20

    def vmem(t):
        # 2x double-buffered windows + double-buffered BlockSpec tiles
        # (planes, p, t)
        return (4 * (t + Lpad + Rpad) + 2 * (ndiags + 2) * t) * itemsize

    while n % (2 * tile) == 0 and vmem(2 * tile) <= budget:
        tile *= 2
    return Lpad, Rpad, tile, align


def cg_phase_a(planes, offsets: tuple, r, p_old, gamma, gamma_prev,
               interpret: bool = False):
    """Phase A of the fused classic-CG iteration: one streamed pass that
    computes ``p = r + beta p_old`` (beta = gamma/gamma_prev, inf -> 0
    on the first iteration), ``t = A p``, and ``(p, t)``.

    The p-update is folded INTO the SpMV's halo windows: p values at
    shifted positions are recomputed from the r/p_old windows already in
    VMEM, so p_old's deferred update costs one extra streamed window
    instead of a separate full pass.  HBM traffic: D plane reads + r
    window + p_old window + p write + t write (~D+4 passes) vs the
    XLA formulation's ~D+7 for the same ops.

    This is the reference's monolithic device-kernel concept
    (``acgsolvercuda_cg_kernel``, ``cg-kernels-cuda.cu:627-970``)
    restated for TPU: the whole iteration as two kernels with scalars
    riding SMEM, leaving nothing for XLA to fuse (the failure mode that
    retired the single fused kernels in round 2 -- BASELINE.md).

    Returns ``(p, t, pdott)``; pdott is a () f32 scalar.
    """
    n = r.shape[0]
    route = fused_cg_route(offsets, n, r.dtype)
    if route is None:
        raise ValueError("shape not supported by the fused CG kernels")
    Lpad, Rpad, tile, align = route
    grid = n // tile
    win = tile + Lpad + Rpad
    kadt = acc_dtype(r.dtype)

    ndiags = len(planes)

    def kernel(scal_ref, r_hbm, p_hbm, *rest):
        plane_refs = rest[:ndiags]
        p_ref, t_ref, dot_ref = rest[ndiags:ndiags + 3]
        rwin_a, rwin_b, pwin_a, pwin_b, sems = rest[ndiags + 3:]
        rwins, pwins = (rwin_a, rwin_b), (pwin_a, pwin_b)
        i = pl.program_id(0)
        beta = (scal_ref[0, 0] / scal_ref[0, 1]).astype(r.dtype)

        # DOUBLE-BUFFERED windows: scratch_shapes persist across the
        # (strictly sequential) TPU grid steps, so step i's compute
        # overlaps step i+1's window DMAs -- the cross-step prefetch
        # Mosaic gives BlockSpec operands, hand-rolled for the halo
        # windows.  Slot selection is static via even/odd duplication;
        # slot s uses semaphores sems[s*6 : s*6+6].
        def starts(step, slot):
            for hbm, wref, s0 in ((r_hbm, rwins[slot], slot * 6),
                                  (p_hbm, pwins[slot], slot * 6 + 3)):
                st, _ = _window_copies(hbm, wref, sems, s0, step, grid,
                                       tile, Lpad, Rpad, align, r.dtype)
                st()

        def waits(step, slot):
            for hbm, wref, s0 in ((r_hbm, rwins[slot], slot * 6),
                                  (p_hbm, pwins[slot], slot * 6 + 3)):
                _, wt = _window_copies(hbm, wref, sems, s0, step, grid,
                                       tile, Lpad, Rpad, align, r.dtype)
                wt()

        def compute(rwin, pwin):
            # p over the whole window (halo positions recomputed from
            # the r/p_old windows -- the deferred-p-update trick).
            # pw is a VALUE; offsets are static, so plain slices compile
            pw = rwin[...] + beta * pwin[...]
            acc = jnp.zeros((tile,), kadt)
            for pr, off in zip(plane_refs, offsets):
                acc = acc + (pr[:].astype(kadt)
                             * pw[Lpad + off:Lpad + off + tile]
                             .astype(kadt))
            p_body = pw[Lpad:Lpad + tile]
            p_ref[:] = p_body
            t_ref[:] = acc.astype(r.dtype)
            return jnp.sum(acc * p_body.astype(kadt))

        # int32-explicit modulo: under jax_enable_x64 a plain `i % 2`
        # promotes through int64, which Mosaic cannot lower
        par = jax.lax.rem(i, jnp.int32(2))

        @pl.when(i == 0)
        def _():
            starts(i, 0)

        for parity in (0, 1):
            @pl.when((par == jnp.int32(parity)) & (i < grid - 1))
            def _(parity=parity):
                starts(i + 1, 1 - parity)

        for parity in (0, 1):
            @pl.when(par == jnp.int32(parity))
            def _(parity=parity):
                waits(i, parity)
                partial = compute(rwins[parity], pwins[parity])

                @pl.when(i == 0)
                def _():
                    dot_ref[0] = partial

                @pl.when(i > 0)
                def _():
                    dot_ref[0] += partial

    tile_spec = pl.BlockSpec((tile,), lambda i: (i,),
                             memory_space=pltpu.VMEM)
    scal = jnp.stack([gamma.astype(jnp.float32),
                      gamma_prev.astype(jnp.float32)]).reshape(1, 2)
    p, t, d = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((1, 2), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)] + [
            tile_spec for _ in planes],
        out_specs=(tile_spec, tile_spec,
                   pl.BlockSpec((1,), lambda i: (0,),
                                memory_space=pltpu.SMEM)),
        out_shape=(jax.ShapeDtypeStruct((n,), r.dtype),
                   jax.ShapeDtypeStruct((n,), r.dtype),
                   jax.ShapeDtypeStruct((1,), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((win,), r.dtype),
                        pltpu.VMEM((win,), r.dtype),
                        pltpu.VMEM((win,), r.dtype),
                        pltpu.VMEM((win,), r.dtype),
                        pltpu.SemaphoreType.DMA((12,))],
        interpret=interpret,
    )(scal, r, p_old, *planes)
    return p, t, d[0]


def cg_phase_b(x, p, r, t, gamma, pdott, interpret: bool = False):
    """Phase B of the fused classic-CG iteration: one streamed pass for
    ``alpha = gamma/(p,t); x += alpha p; r -= alpha t`` and the next
    ``gamma = (r, r)`` accumulated in SMEM.  Returns (x, r, gamma)."""
    n = x.shape[0]
    tile = TILE if n % TILE == 0 and n >= TILE else None
    if tile is None:
        raise ValueError("shape not supported by the fused CG kernels")
    grid = n // tile
    kadt = acc_dtype(x.dtype)

    def kernel(scal_ref, x_ref, p_ref, r_ref, t_ref, xo, ro, go):
        i = pl.program_id(0)
        alpha = (scal_ref[0, 0] / scal_ref[0, 1]).astype(x.dtype)
        xo[:] = x_ref[:] + alpha * p_ref[:]
        rn = r_ref[:] - alpha * t_ref[:]
        ro[:] = rn
        partial = jnp.sum(rn.astype(kadt) * rn.astype(kadt))

        @pl.when(i == 0)
        def _():
            go[0] = partial

        @pl.when(i > 0)
        def _():
            go[0] += partial

    tile_spec = pl.BlockSpec((tile,), lambda i: (i,),
                             memory_space=pltpu.VMEM)
    scal = jnp.stack([gamma.astype(jnp.float32),
                      pdott.astype(jnp.float32)]).reshape(1, 2)
    xn, rn, g = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((1, 2), lambda i: (0, 0),
                               memory_space=pltpu.SMEM)] + [tile_spec] * 4,
        out_specs=(tile_spec, tile_spec,
                   pl.BlockSpec((1,), lambda i: (0,),
                                memory_space=pltpu.SMEM)),
        out_shape=(jax.ShapeDtypeStruct((n,), x.dtype),
                   jax.ShapeDtypeStruct((n,), x.dtype),
                   jax.ShapeDtypeStruct((1,), jnp.float32)),
        interpret=interpret,
    )(scal, x, p, r, t)
    return xn, rn, g[0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_pipelined_update(x, r, w, p, t, z, q, alpha, beta,
                           interpret: bool = False):
    """One-pass Ghysels-Vanroose update (``cg-kernels-cuda.cu:187-269``):

        z = q + beta z;  t = w + beta t;  p = r + beta p
        x = x + alpha p; r = r - alpha t; w = w - alpha z

    Returns (x, r, w, p, t, z).  alpha/beta ride in SMEM (the reference
    reads them from device memory to avoid host syncs; same idea).
    """
    n = x.shape[0]
    ab = jnp.stack([alpha.astype(x.dtype), beta.astype(x.dtype)]).reshape(1, 2)
    vecs = [_pad_to(v, TILE) for v in (x, r, w, p, t, z, q)]
    npad = vecs[0].shape[0]
    grid = npad // TILE

    def kernel(ab_ref, x_ref, r_ref, w_ref, p_ref, t_ref, z_ref, q_ref,
               xo, ro, wo, po, to, zo):
        a = ab_ref[0, 0]
        b = ab_ref[0, 1]
        zn = q_ref[:] + b * z_ref[:]
        tn = w_ref[:] + b * t_ref[:]
        pn = r_ref[:] + b * p_ref[:]
        xo[:] = x_ref[:] + a * pn
        ro[:] = r_ref[:] - a * tn
        wo[:] = w_ref[:] - a * zn
        po[:] = pn
        to[:] = tn
        zo[:] = zn

    tile_spec = pl.BlockSpec((TILE,), lambda i: (i,),
                             memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((1, 2), lambda i: (0, 0),
                               memory_space=pltpu.SMEM)] + [tile_spec] * 7,
        out_specs=(tile_spec,) * 6,
        out_shape=tuple(jax.ShapeDtypeStruct((npad,), x.dtype)
                        for _ in range(6)),
        interpret=interpret,
    )(ab, *vecs)
    return tuple(o[:n] for o in outs)


# -- matrix-free stencil SpMV (the operator tier's Pallas path) -----------

def stencil_spmv_route(op, n_total: int, dtype):
    """``(Lpad, Rpad, tile, align)`` when the in-kernel-generated
    stencil SpMV supports this operator/shape, else None.  Constant-
    coefficient Poisson on the single-window band (the ``dia_spmv``
    "fast" shape): the whole point of the kernel is that NO plane
    inputs exist -- x streams through VMEM once and the coefficient
    masks are computed from iotas in-register -- so the VMEM budget is
    looser than the assembled kernel's, but the band/divisibility
    constraints are the same."""
    if getattr(op, "kind", None) != "poisson":
        return None
    route = dia_spmv_route(op.offsets, n_total, dtype,
                           ndiags=len(op.offsets))
    if route[0] != "fast":
        return None
    Lpad, Rpad, tile, align = route[1:]
    if tile % LANE:
        return None
    return Lpad, Rpad, tile, align


@functools.partial(jax.jit,
                   static_argnames=("n", "dim", "offsets", "Lpad",
                                    "Rpad", "tile", "align", "interpret"))
def _stencil_poisson_call(x, n: int, dim: int, offsets: tuple,
                          Lpad: int, Rpad: int, tile: int, align: int,
                          interpret: bool):
    N = x.shape[0]
    grid = N // tile
    win = tile + Lpad + Rpad
    sub = tile // LANE

    def kernel(x_hbm, y_ref):
        i = pl.program_id(0)

        def body(xwin, sems):
            start, wait = _window_copies(x_hbm, xwin, sems, 0, i, grid,
                                         tile, Lpad, Rpad, align,
                                         x.dtype)
            start()
            wait()
            kadt = acc_dtype(x.dtype)
            # global row indices of this tile, as a native 2-D tile
            # (TPU iotas want >= 2 dims); masks derive from the grid
            # coordinate exactly like ops.operator.stencil_planes
            r2 = jax.lax.broadcasted_iota(jnp.int32, (sub, LANE), 0)
            c2 = jax.lax.broadcasted_iota(jnp.int32, (sub, LANE), 1)
            gidx = i * tile + r2 * LANE + c2
            acc = jnp.zeros((sub, LANE), kadt)
            for off in offsets:
                xs = xwin[pl.ds(Lpad + off, tile)].reshape(
                    sub, LANE).astype(kadt)
                # the generated plane VALUE, in exactly dia_mv's
                # ``y + plane * x`` expression shape so XLA forms the
                # same multiply-add chain as the assembled/XLA path
                if off == 0:
                    plane = jnp.full((sub, LANE), float(2 * dim), kadt)
                else:
                    stride = abs(int(off))
                    coord = (gidx // stride) % n
                    mask = coord > 0 if off < 0 else coord < n - 1
                    plane = jnp.where(mask, -1.0, 0.0).astype(kadt)
                acc = acc + plane * xs
            y_ref[:] = acc.reshape(tile).astype(x.dtype)

        pl.run_scoped(body, pltpu.VMEM((win,), x.dtype),
                      pltpu.SemaphoreType.DMA((3,)))

    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((tile,), lambda i: (i,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N,), x.dtype),
        interpret=interpret,
    )(x)


def stencil_spmv(op, x, interpret: bool = False, tile: int | None = None,
                 align: int | None = None):
    """y = A @ x for a matrix-free :class:`~acg_tpu.ops.operator.
    StencilOperator` with the coefficient masks generated IN-KERNEL:
    x streams through VMEM once per row tile (``_window_copies``, the
    single-x-pass machinery the assembled DIA kernel uses) and the
    plane values never exist anywhere -- not in HBM, not in VMEM.
    This is the matrix-free restatement of :func:`dia_spmv`'s traffic
    argument: the assembled kernel still reads D planes per tile; this
    one reads x and writes y, full stop.

    Values are bitwise-equal to the XLA matfree apply (-1 * x == -x;
    masked positions add a zero, exactly like the structural-zero
    plane entries).  Shapes outside the single-window route -- or
    non-Poisson kinds -- fall back to the operator's own XLA apply
    (``op.matfree_apply``), the same degrade discipline as
    ``dia_spmv``'s "xla" route.  ``tile``/``align`` override the route
    for interpret-mode tests at small sizes."""
    n_total = x.shape[0]
    if tile is not None:
        n, dim = op.grid
        band = n ** (dim - 1)
        Lpad = Rpad = band + (-band) % (align or 1)
        if (tile % LANE or n_total % tile or band > tile
                or op.kind != "poisson"):
            return op.matfree_apply(x)
        return _stencil_poisson_call(x, n, dim, op.offsets, Lpad, Rpad,
                                     tile, align or 1, interpret)
    route = stencil_spmv_route(op, n_total, x.dtype)
    if route is None:
        return op.matfree_apply(x)
    Lpad, Rpad, rtile, ralign = route
    n, dim = op.grid
    return _stencil_poisson_call(x, n, dim, op.offsets, Lpad, Rpad,
                                 rtile, ralign, interpret)
