"""Live solve observatory: in-flight status, run-history ledger, and
SLO burn tracking -- the observability plane a long-lived service
mounts.

Every observability surface so far is POST-HOC: the stats block,
convergence traces, soak percentiles, and timeline exports all land
after the solve exits.  The reference paper's device-initiated solver
is an opaque persistent loop the host cannot watch mid-flight
(PAPER.md), and global-reduction-pipelined variants (arXiv:1905.06850)
make mid-solve stall attribution harder still -- exactly the blindness
a live status plane exists to remove.  Three legs, all DISARMED by
default (the metrics/tracing ``arm()`` design; disarmed programs stay
byte-identical -- every hook here is host-side bookkeeping, pinned in
tests/test_hlo_structure.py and tests/test_observatory.py):

1. **Live in-flight status** (``--status-port P`` / ``--status-file
   F``): a process-wide :class:`SolveStatus` recorder fed from hooks
   the layers already have -- the ``--progress`` heartbeat, the
   checkpoint chunk drivers' per-chunk carry returns (real
   iteration/residual samples mid-solve), the soak driver's per-solve
   indices, and resilience/health/checkpoint events -- served as a
   JSON document (schema ``acg-tpu-status/1``) over a stdlib
   daemon-thread HTTP endpoint (the ``--metrics-port`` design; the
   status server also answers ``/metrics`` so one port can serve
   both).  The document carries phase, iteration, residual-trail
   sparkline data, iterations/sec, an ETA projected from the
   numerical-health tier's Lanczos kappa CG-bound (falling back to the
   measured residual-decay rate, then the iteration cap), per-part
   imbalance, the last K structured events, and soak progress.
2. **Run-history ledger** (``--history DIR``): every solve appends its
   ``--stats-json`` document to a date-partitioned JSONL ledger, one
   index line per solve (matrix id, tier, precond, dtype, latency,
   iterations, schema version) carrying the full document under its
   ``doc`` key.  ``scripts/history_report.py`` renders per-case trend
   tables and ``perfmodel.check_regression`` /
   ``scripts/bench_diff.py`` accept a ledger directory as the
   baseline, picking the best-known USABLE prior capture and skipping
   ``bench_backend_unavailable`` entries (the BENCH_r05 stale-baseline
   trap).
3. **SLO tracking** (``--slo latency=S,iters=N,gap=G``): declared
   objectives become ``acg_slo_target`` / ``acg_slo_breaches_total`` /
   ``acg_slo_burn_ratio`` families on the existing Prometheus
   registry, breaches emit structured events into the
   telemetry/timeline stream, and ``--fail-on-slo`` gates the exit
   code (:data:`SLO_EXIT_CODE`) like the soak drift gate.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import os
import sys
import threading
import time

__all__ = [
    "STATUS_SCHEMA", "HISTORY_SCHEMA", "SLO_EXIT_CODE",
    "SolveStatus", "STATUS", "arm", "disarm", "armed", "shutdown",
    "begin_solve", "end_solve", "note_chunk", "note_event",
    "note_imbalance", "note_kappa", "note_soak_solve", "note_solver",
    "progress_sample", "heartbeat_line",
    "serve_status", "set_status_file", "flush_status", "status_document",
    "history_append", "history_scan", "load_history_baseline",
    "SloSpec", "parse_slo", "install_slo", "installed_slo",
    "slo_observe", "slo_report", "slo_breached", "attach_slo",
]

STATUS_SCHEMA = "acg-tpu-status/1"
HISTORY_SCHEMA = "acg-tpu-history/1"
# residual-trail samples the status document serves (sparkline data);
# also the window the measured-rate ETA is fit over
TRAIL_CAPACITY = 64
# last K structured events mirrored into the status document
EVENT_CAPACITY = 16
# minimum seconds between --status-file rewrites: heartbeats can fire
# thousands of times per second on a tiny solve, and the file sink must
# not turn the observability plane into an I/O workload
STATUS_FILE_INTERVAL = 0.2
# CLI exit code for a tripped --fail-on-slo gate (the process-wide
# contract lives in errors.ExitCode; --buildinfo renders the table)
from acg_tpu.errors import ExitCode as _ExitCode

SLO_EXIT_CODE = int(_ExitCode.SLO_BREACH)


def _finite(v) -> float | None:
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    return v if math.isfinite(v) else None


class SolveStatus:
    """The process-wide in-flight status recorder.

    Thread-safe (one lock; the HTTP serving thread and the solving
    thread share it); every mutator is a cheap early-return while the
    layer is disarmed, and all recording is host-side bookkeeping --
    arming cannot perturb the compiled solver programs."""

    def __init__(self):
        self._lock = threading.RLock()
        self._reset_locked()

    def _reset_locked(self) -> None:
        self.phase: str | None = None
        self.solve: dict = {}
        self.trail: collections.deque = collections.deque(
            maxlen=TRAIL_CAPACITY)
        self.events: collections.deque = collections.deque(
            maxlen=EVENT_CAPACITY)
        self.imbalance: dict | None = None
        self.soak: dict | None = None
        self.kappa: dict | None = None
        self.degraded: dict | None = None
        self.solves_completed = 0
        self.armed_since: float | None = None

    def reset(self) -> None:
        with self._lock:
            self._reset_locked()

    # -- feeding --------------------------------------------------------

    def begin(self, what: str, maxits: int, rtol: float = 0.0,
              atol: float = 0.0, matrix=None, nparts: int = 1) -> None:
        with self._lock:
            self.trail.clear()
            self.solve = {
                "what": str(what),
                "active": True,
                "iteration": 0,
                "residual": None,
                "maxits": int(maxits),
                "rtol": float(rtol),
                "atol": float(atol),
                "target": None,
                "matrix": (str(matrix) if matrix is not None else None),
                "nparts": int(nparts),
                "started_unix": time.time(),
            }

    def sample(self, what: str, iteration: int, residual) -> None:
        """One in-flight (iteration, residual) observation -- from the
        heartbeat callback or a checkpoint chunk boundary."""
        with self._lock:
            it = int(iteration)
            if self.trail and it < self.trail[-1][1]:
                # iteration went backwards: a new solve (or a rollback)
                # started -- a rate fit across the seam would be
                # nonsense, so the trail restarts
                self.trail.clear()
            self.trail.append((time.time(), it, _finite(residual)))
            if not self.solve:
                self.solve = {"what": str(what), "maxits": 0,
                              "rtol": 0.0, "atol": 0.0, "target": None,
                              "started_unix": time.time()}
            self.solve["active"] = True
            self.solve["iteration"] = it
            self.solve["residual"] = _finite(residual)

    def finish(self, converged: bool, iterations: int,
               seconds: float) -> None:
        with self._lock:
            if self.solve:
                self.solve["active"] = False
                self.solve["converged"] = bool(converged)
                self.solve["iteration"] = int(iterations)
                self.solve["seconds"] = float(seconds)
            self.solves_completed += 1

    def note_target(self, abs_tol) -> None:
        with self._lock:
            if self.solve:
                self.solve["target"] = _finite(abs_tol)

    def note_latency(self, seconds: float) -> None:
        with self._lock:
            if self.solve:
                self.solve["seconds"] = float(seconds)

    def note_batch(self, nrhs: int, residuals, converged) -> None:
        """Per-RHS evidence of a batched solve (acg_tpu.solvers.
        batched): the status document's ``solve.batch`` block names
        the SLOWEST unconverged RHS -- the column the ETA is keyed to,
        since the batched loop runs exactly until it converges."""
        with self._lock:
            if not self.solve:
                self.solve = {"what": "batched", "maxits": 0,
                              "rtol": 0.0, "atol": 0.0, "target": None,
                              "started_unix": time.time()}
            res = [_finite(r) for r in residuals]
            conv = [bool(c) for c in converged]
            unconv = [i for i, c in enumerate(conv) if not c]
            pool = unconv if unconv else list(range(len(res)))
            slowest = max(pool, key=lambda i: (res[i]
                                               if res[i] is not None
                                               else float("inf"))) \
                if pool else 0
            self.solve["batch"] = {
                "nrhs": int(nrhs),
                "unconverged": len(unconv),
                "slowest_rhs": int(slowest),
                "slowest_residual": res[slowest] if res else None,
                "residuals": res,
            }

    def note_phase(self, name: str) -> None:
        with self._lock:
            self.phase = str(name)

    def note_event(self, kind: str, detail: str) -> None:
        with self._lock:
            self.events.append({"t": time.time(), "kind": str(kind),
                                "detail": str(detail)})

    def note_imbalance(self, imbalance: dict) -> None:
        with self._lock:
            self.imbalance = dict(imbalance)

    def note_soak(self, i: int, nsolves: int) -> None:
        with self._lock:
            self.soak = {"solve": int(i), "nsolves": int(nsolves)}

    def note_degraded(self, frm, to, reason: str) -> None:
        """The supervisor relaunched this process on a SHRUNKEN mesh:
        the status document must say so (``degraded: {from, to,
        reason}``) -- a poller watching a degraded solve should not
        mistake it for the full-capacity run."""
        with self._lock:
            self.degraded = {"from": int(frm), "to": int(to),
                             "reason": str(reason)}

    def note_kappa(self, kappa, predicted_total=None) -> None:
        k = _finite(kappa)
        if k is None or k <= 0:
            return
        with self._lock:
            self.kappa = {"kappa": k}
            if predicted_total:
                self.kappa["predicted_iterations"] = int(predicted_total)

    # -- deriving -------------------------------------------------------

    def rates(self) -> tuple[float | None, float | None, str | None]:
        """``(iterations_per_second, eta_seconds, eta_source)`` from
        the current trail.  The remaining-iterations estimate prefers
        the Lanczos kappa CG-bound (the numerical-health tier's
        predicted total), falls back to the measured residual-decay
        rate toward the absolute target, then to the iteration cap."""
        with self._lock:
            trail = list(self.trail)
            solve = dict(self.solve)
            kap = dict(self.kappa) if self.kappa else {}
        ips = None
        if len(trail) >= 2:
            t0, k0, _ = trail[0]
            t1, k1, _ = trail[-1]
            if t1 > t0 and k1 > k0:
                ips = (k1 - k0) / (t1 - t0)
        k = int(solve.get("iteration") or (trail[-1][1] if trail else 0))
        remaining = source = None
        pred = kap.get("predicted_iterations")
        if pred and pred > k:
            remaining, source = pred - k, "kappa-bound"
        if remaining is None:
            remaining, source = self._decay_remaining(trail, solve)
        if remaining is None:
            maxits = int(solve.get("maxits") or 0)
            if maxits > k:
                remaining, source = maxits - k, "iteration-cap"
        eta = (remaining / ips) if (ips and remaining is not None) \
            else None
        return ips, eta, (source if eta is not None else None)

    @staticmethod
    def _decay_remaining(trail, solve):
        """Iterations left to reach the absolute residual target at the
        measured log-residual decay rate over the trail window."""
        target = _finite(solve.get("target"))
        if not target or target <= 0 or len(trail) < 2:
            return None, None
        pts = [(k, r) for _, k, r in trail if r is not None and r > 0]
        if len(pts) < 2:
            return None, None
        (k0, r0), (k1, r1) = pts[0], pts[-1]
        if k1 <= k0 or r1 >= r0:
            return None, None   # not converging over this window
        if r1 <= target:
            return 0, "measured-rate"
        decay = (math.log(r1) - math.log(r0)) / (k1 - k0)   # < 0
        rem = int(math.ceil(math.log(target / r1) / decay))
        return max(rem, 0), "measured-rate"

    def document(self) -> dict:
        """The ``acg-tpu-status/1`` JSON document served to pollers."""
        ips, eta, source = self.rates()
        with self._lock:
            solve = dict(self.solve)
            doc: dict = {
                "schema": STATUS_SCHEMA,
                "unix_time": time.time(),
                "pid": os.getpid(),
                "armed_since": self.armed_since,
                "phase": self.phase,
                "solves_completed": self.solves_completed,
                "residual_trail": [[k, r] for _, k, r in self.trail],
            }
            if solve:
                solve["iterations_per_second"] = ips
                solve["eta_seconds"] = eta
                solve["eta_source"] = source
                if solve.get("started_unix"):
                    solve["elapsed_seconds"] = (time.time()
                                                - solve["started_unix"])
                doc["solve"] = solve
            if self.kappa:
                doc["kappa"] = dict(self.kappa)
            if self.imbalance:
                doc["imbalance"] = dict(self.imbalance)
            if self.soak:
                doc["soak"] = dict(self.soak)
            if self.degraded:
                doc["degraded"] = dict(self.degraded)
            if self.events:
                doc["events"] = list(self.events)
        rep = slo_report()
        if rep:
            doc["slo"] = rep
        peers = _peers_block()
        if peers is not None:
            doc["peers"] = peers
        return doc


STATUS = SolveStatus()

# the erragree DeadlineHeartbeat this run started (--heartbeat with a
# status plane armed): the status document's peers: block reads its
# per-peer beat ages.  Duck-typed -- anything with peer_ages() and a
# deadline attribute serves (tests use a stub).
_heartbeat = None

# the supervisor tells a relaunched child it runs on a shrunken mesh
# through this env var ("FROM:TO:REASON"); arm() folds it into the
# status document's degraded key
DEGRADED_ENV = "ACG_TPU_DEGRADED"


def set_heartbeat(hb) -> None:
    """Attach the run's dead-peer heartbeat so the status document can
    expose per-peer liveness (``peers:``)."""
    global _heartbeat
    _heartbeat = hb


def _peers_block() -> dict | None:
    hb = _heartbeat
    if hb is None:
        return None
    try:
        ages = hb.peer_ages()
    except Exception:  # noqa: BLE001 -- a torn-down heartbeat must
        return None    # never break a status scrape
    return {
        "deadline_seconds": float(getattr(hb, "deadline", 0.0)),
        "last_beat_age_seconds": {str(q): round(float(a), 3)
                                  for q, a in sorted(ages.items())},
    }


_armed = False
_status_file: str | None = None
_last_flush = 0.0
# one writer at a time: the heartbeat callback thread and the solving
# thread both reach _maybe_flush, and two writers sharing the per-pid
# temp name would interleave INSIDE it -- renaming torn JSON into place
_flush_lock = threading.Lock()


def arm() -> None:
    """Arm the process-wide status recorder.  All recording is
    host-side bookkeeping, so arming cannot perturb the compiled
    programs (the metrics/tracing arm() contract)."""
    global _armed
    _armed = True
    if STATUS.armed_since is None:
        STATUS.armed_since = time.time()
    env = os.environ.get(DEGRADED_ENV)
    if env:
        # a supervisor relaunch on a shrunken mesh announces itself
        try:
            frm, to, reason = env.split(":", 2)
            STATUS.note_degraded(int(frm), int(to), reason)
        except ValueError:
            sys.stderr.write(f"acg-tpu: {DEGRADED_ENV}={env!r} is not "
                             f"FROM:TO:REASON; ignored\n")


def disarm() -> None:
    global _armed
    _armed = False


def armed() -> bool:
    return _armed


def shutdown() -> None:
    """End-of-invocation cleanup (the CLI's finally): a final status
    flush with the solve marked over, then disarm and clear -- an
    in-process caller (tests, library use) must never observe a stale
    run's status or SLO state."""
    global _status_file
    if _armed and _status_file:
        try:
            STATUS.note_phase("exited")
            flush_status(force=True)
        except OSError as e:
            sys.stderr.write(f"acg-tpu: --status-file {_status_file}: "
                             f"{e}\n")
    disarm()
    _status_file = None
    set_heartbeat(None)
    STATUS.reset()
    _clear_slo()


# -- feeding hooks (cheap early-returns while disarmed) -------------------

def begin_solve(what: str, maxits: int, rtol: float = 0.0,
                atol: float = 0.0, matrix=None, nparts: int = 1) -> None:
    """The run header.  Unconditional (the progress_sample contract):
    pure host bookkeeping, and the ``--progress`` heartbeat's ETA
    needs the iteration cap even when no status sink is armed."""
    STATUS.begin(what, maxits, rtol=rtol, atol=atol, matrix=matrix,
                 nparts=nparts)
    _maybe_flush()


def end_solve(converged: bool, iterations: int, seconds: float) -> None:
    """Solve close-out (every solver tail via metrics.record_solve);
    unconditional like begin_solve, so the recorder's active flag and
    solve counter stay truthful whether or not a sink is armed."""
    STATUS.finish(converged, iterations, seconds)
    _maybe_flush()


def note_chunk(what: str, iteration: int, residual, abs_tol=None,
               trace=None, rtol: float = 0.0) -> None:
    """One checkpoint-chunk boundary (the chunk drivers' per-dispatch
    carry return): a REAL mid-solve iteration/residual sample, plus --
    when the telemetry ring rode the chunk -- a Lanczos kappa estimate
    refresh so the ETA can ride the CG bound."""
    if not _armed:
        return
    STATUS.sample(what, iteration, residual)
    if abs_tol is not None:
        STATUS.note_target(abs_tol)
    if trace is not None:
        _kappa_from_trace(trace, rtol or STATUS.solve.get("rtol", 0.0))
    _maybe_flush()


def _kappa_from_trace(trace, rtol) -> None:
    """Refresh the kappa/predicted-iterations estimate from an in-loop
    convergence trace (host-side, a tridiagonal eig of at most the ring
    window -- cheap at chunk cadence; never sinks a solve)."""
    try:
        from acg_tpu.health import predicted_iterations, spectrum_estimate
        est = spectrum_estimate(trace)
        kappa = (est or {}).get("kappa")
        if not kappa:
            return
        STATUS.note_kappa(kappa, predicted_iterations(kappa, rtol))
    except Exception:  # noqa: BLE001 -- observability must never sink
        pass           # the solve it watches


def note_event(kind: str, detail: str) -> None:
    if not _armed:
        return
    STATUS.note_event(kind, detail)
    _maybe_flush()


def note_phase(name: str) -> None:
    if not _armed:
        return
    STATUS.note_phase(name)


def note_imbalance(imbalance: dict) -> None:
    if not _armed:
        return
    STATUS.note_imbalance(imbalance)


def note_kappa(kappa, predicted_total=None) -> None:
    if not _armed:
        return
    STATUS.note_kappa(kappa, predicted_total)


def note_soak_solve(i: int, nsolves: int, latency: float) -> None:
    """One completed soak solve (the soak driver's per-solve tail).
    Only the queue-progress note plus the driver's own timed latency
    (dispatch included -- what a serving fleet experiences): iteration
    counts were already closed out by the solver tail's
    ``metrics.record_solve`` hook."""
    if not _armed:
        return
    STATUS.note_soak(i + 1, nsolves)
    STATUS.note_latency(latency)
    _maybe_flush()


def note_batch(nrhs: int, residuals, converged) -> None:
    """Per-RHS residual/convergence columns of a batched solve (the
    status document's ``solve.batch`` block; the ETA keys to the
    slowest unconverged RHS).  No-op disarmed."""
    if not _armed:
        return
    STATUS.note_batch(nrhs, residuals, converged)
    _maybe_flush()


def note_solver(solver) -> None:
    """Per-part size/imbalance from the telemetry tier's rank payload
    (the PR-2 aggregation), recorded once a partitioned solver exists."""
    if not _armed:
        return
    try:
        from acg_tpu import telemetry
        inner = solver
        while hasattr(inner, "inner"):
            inner = inner.inner
        payload = telemetry.rank_payload(inner)
        agg = telemetry.aggregate_ranks([payload])
        parts = agg.get("parts")
        if parts:
            STATUS.note_imbalance(parts)
    except Exception:  # noqa: BLE001 -- observability must never sink
        pass           # the solve it watches


# -- the heartbeat's numbers ---------------------------------------------

def progress_sample(what: str, iteration: int, residual
                    ) -> tuple[float | None, float | None]:
    """Feed one ``--progress`` heartbeat observation and return
    ``(iterations_per_second, eta_seconds)`` -- the same numbers the
    status endpoint serves.  Records unconditionally (the heartbeat
    only fires when --progress armed it; its rate bookkeeping is what
    makes the line's numbers possible even without a status sink)."""
    STATUS.sample(what, iteration, residual)
    if _armed:
        _maybe_flush()
    ips, eta, _source = STATUS.rates()
    return ips, eta


def _fmt_eta(seconds: float) -> str:
    if seconds >= 3600:
        return f"{seconds / 3600:.1f}h"
    if seconds >= 60:
        return f"{seconds / 60:.1f}m"
    return f"{seconds:.1f}s"


def heartbeat_line(what: str, iteration: int, rnrm2: float) -> str:
    """The ``--progress`` heartbeat line, shared by the compiled loops'
    callback and the host oracle so every tier prints the same shape:
    iteration, residual, and -- once two samples exist -- the measured
    iterations/sec and ETA."""
    ips, eta = progress_sample(what, iteration, rnrm2)
    line = (f"acg-tpu: {what}: iteration {int(iteration)}: "
            f"residual 2-norm {float(rnrm2):.6e}")
    if ips is not None:
        line += f", {ips:,.1f} it/s"
        if eta is not None:
            line += f", ETA {_fmt_eta(eta)}"
    return line


# -- sinks ----------------------------------------------------------------

def status_document() -> dict:
    return STATUS.document()


def set_status_file(path) -> None:
    global _status_file
    _status_file = os.fspath(path)


def flush_status(force: bool = False) -> None:
    """Write the status document to ``--status-file`` with atomic
    rename (a poller never reads torn JSON -- the metrics-textfile
    contract), throttled to :data:`STATUS_FILE_INTERVAL`.  Serialised
    under one lock: the throttle check and the temp-file write must be
    one unit, or two threads passing the check together would
    interleave writes into the shared temp name."""
    global _last_flush
    if _status_file is None:
        return
    with _flush_lock:
        if _status_file is None:
            return
        now = time.monotonic()
        if not force and now - _last_flush < STATUS_FILE_INTERVAL:
            return
        _last_flush = now
        tmp = f"{_status_file}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(status_document(), f)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, _status_file)


def _maybe_flush() -> None:
    if _status_file is None:
        return
    try:
        flush_status()
    except OSError:
        pass  # a full disk must not sink the solve it watches


def serve_status(port: int):
    """Serve ``GET /status`` (the acg-tpu-status/1 JSON document) on a
    daemon thread -- the ``--metrics-port`` design.  The handler also
    answers ``/metrics`` with the Prometheus exposition, so one port
    can serve both planes (``--status-port`` == ``--metrics-port`` is
    explicitly supported).  Returns the live server
    (``.server_address[1]`` is the real port; pass 0 to let the OS
    pick, the test hook)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 -- stdlib handler contract
            path = self.path.split("?")[0]
            if path in ("/status", "/"):
                body = json.dumps(status_document()).encode()
                ctype = "application/json"
            elif path == "/metrics":
                from acg_tpu import metrics
                body = metrics.expose().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # pollers must not spam stderr
            pass

    server = ThreadingHTTPServer(("", int(port)), _Handler)
    t = threading.Thread(target=server.serve_forever,
                         name="acg-status", daemon=True)
    t.start()
    return server


# -- SLO tracking ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SloSpec:
    """Declared per-solve objectives (``--slo latency=S,iters=N,gap=G``,
    any subset): solve latency in seconds, iterations-to-converge, and
    the numerical-health audit gap."""

    latency_s: float | None = None
    iters: int | None = None
    gap: float | None = None

    def targets(self) -> dict:
        out = {}
        if self.latency_s is not None:
            out["latency"] = float(self.latency_s)
        if self.iters is not None:
            out["iters"] = float(self.iters)
        if self.gap is not None:
            out["gap"] = float(self.gap)
        return out

    def __str__(self) -> str:
        bits = []
        if self.latency_s is not None:
            bits.append(f"latency={self.latency_s:g}")
        if self.iters is not None:
            bits.append(f"iters={self.iters}")
        if self.gap is not None:
            bits.append(f"gap={self.gap:g}")
        return ",".join(bits)


def parse_slo(spec: str) -> SloSpec:
    """Parse ``latency=S,iters=N,gap=G`` (any non-empty subset, any
    order); every target must be positive."""
    kw: dict = {}
    for item in str(spec).split(","):
        item = item.strip()
        if not item:
            continue
        key, sep, val = item.partition("=")
        key = key.strip()
        if not sep or key not in ("latency", "iters", "gap"):
            raise ValueError(
                f"invalid --slo objective {item!r}: expected "
                f"latency=SECONDS, iters=N and/or gap=G")
        try:
            v = int(val) if key == "iters" else float(val)
        except ValueError:
            raise ValueError(f"invalid --slo value {val!r} for {key}")
        if v <= 0:
            raise ValueError(f"--slo {key} must be positive, got {val}")
        kw["latency_s" if key == "latency" else key] = v
    if not kw:
        raise ValueError("empty --slo spec: declare at least one of "
                         "latency=S, iters=N, gap=G")
    return SloSpec(**kw)


_slo: SloSpec | None = None
_slo_lock = threading.Lock()
_slo_observed: dict = {}
_slo_breaches: dict = {}
_slo_last: dict = {}


def install_slo(spec: SloSpec) -> None:
    """Arm the declared objectives: target gauges land on the metrics
    registry immediately (a scrape shows what the run promised even
    before the first solve)."""
    global _slo
    from acg_tpu import metrics
    _clear_slo()
    _slo = spec
    for objective, target in spec.targets().items():
        metrics.record_slo_target(objective, target)


def installed_slo() -> SloSpec | None:
    return _slo


def _clear_slo() -> None:
    global _slo
    with _slo_lock:
        _slo = None
        _slo_observed.clear()
        _slo_breaches.clear()
        _slo_last.clear()


def slo_observe(stats=None, latency=None, iterations=None,
                gap=None) -> bool:
    """Judge one completed solve against the declared objectives.
    Returns True when any objective breached; every breach bumps
    ``acg_slo_breaches_total``, refreshes ``acg_slo_burn_ratio`` (the
    cumulative fraction of observed solves breaching -- the error
    budget burned so far), and emits a structured ``slo-breach`` event
    into the telemetry/timeline stream when ``stats`` is given."""
    spec = _slo
    if spec is None:
        return False
    from acg_tpu import metrics
    observed = {}
    if spec.latency_s is not None and latency is not None:
        observed["latency"] = (float(latency), spec.latency_s, "s")
    if spec.iters is not None and iterations is not None:
        observed["iters"] = (float(iterations), float(spec.iters), "")
    if spec.gap is not None and gap is not None \
            and _finite(gap) is not None:
        observed["gap"] = (float(gap), spec.gap, "")
    any_breach = False
    for objective, (value, target, unit) in observed.items():
        breached = value > target
        with _slo_lock:
            _slo_observed[objective] = _slo_observed.get(objective,
                                                         0) + 1
            if breached:
                _slo_breaches[objective] = _slo_breaches.get(objective,
                                                             0) + 1
            _slo_last[objective] = value
            burn = (_slo_breaches.get(objective, 0)
                    / _slo_observed[objective])
        metrics.record_slo(objective, breached, burn)
        if breached:
            any_breach = True
            msg = (f"SLO breach: {objective} {value:g}{unit} > target "
                   f"{target:g}{unit} (burn "
                   f"{burn * 100.0:.0f}% of observed solves)")
            if stats is not None:
                from acg_tpu import telemetry
                telemetry.record_event(stats, "slo-breach", msg)
            else:
                note_event("slo-breach", msg)
            sys.stderr.write(f"acg-tpu: {msg}\n")
    return any_breach


def slo_report() -> dict:
    """The JSON-able ``slo`` section (the stats twin's /8 additive key
    and the status document's ``slo`` entry)."""
    spec = _slo
    if spec is None:
        return {}
    with _slo_lock:
        rep: dict = {"targets": spec.targets(),
                     "observed": dict(_slo_observed),
                     "breaches": dict(_slo_breaches),
                     "last": dict(_slo_last)}
        rep["burn"] = {
            obj: (_slo_breaches.get(obj, 0) / n if n else 0.0)
            for obj, n in _slo_observed.items()}
        rep["breached"] = any(_slo_breaches.values())
    return rep


def slo_breached() -> bool:
    with _slo_lock:
        return any(_slo_breaches.values())


def attach_slo(stats) -> None:
    """Record the SLO verdict onto ``stats.slo`` (the ``slo:`` stats
    section and its --stats-json twin; no-op without declared
    objectives)."""
    rep = slo_report()
    if rep:
        stats.slo = rep


def slo_exit_code(fail_on_slo: bool) -> int:
    """The ``--fail-on-slo`` verdict: 0, or :data:`SLO_EXIT_CODE` when
    the gate is set and any objective breached."""
    return SLO_EXIT_CODE if (fail_on_slo and slo_breached()) else 0


# -- run-history ledger ---------------------------------------------------

def _index_of(doc: dict) -> dict:
    """The ledger index fields for one --stats-json document: enough to
    scan trends without parsing the full document."""
    man = doc.get("manifest") or {}
    st = doc.get("stats") or {}
    soak = st.get("soak") or {}
    lat = (soak.get("latency") or {}).get("p50")
    if lat is None:
        lat = st.get("tsolve")
    case = value = None
    try:
        from acg_tpu.perfmodel import _doc_case
        c = _doc_case(doc)
        if c is not None:
            case, value = c
    except Exception:  # noqa: BLE001 -- an unparseable case still gets
        pass           # a ledger row; it just never baselines
    return {
        "ledger": HISTORY_SCHEMA,
        "unix_time": float(man.get("unix_time") or time.time()),
        "schema": doc.get("schema"),
        "matrix": man.get("matrix"),
        "solver": man.get("solver"),
        "nparts": man.get("nparts"),
        "precond": man.get("precond"),
        "dtype": man.get("dtype"),
        "converged": st.get("converged"),
        "iterations": st.get("niterations"),
        "latency_s": _finite(lat),
        "case": case,
        "value": value,
    }


def history_append(dirpath, doc: dict) -> str:
    """Append one solve's stats document to the date-partitioned
    ledger: ``DIR/YYYY-MM-DD.jsonl``, one index line per solve carrying
    the full document under ``doc``.  Returns the ledger file path."""
    dirpath = os.fspath(dirpath)
    os.makedirs(dirpath, exist_ok=True)
    idx = _index_of(doc)
    day = time.strftime("%Y-%m-%d", time.gmtime(idx["unix_time"]))
    path = os.path.join(dirpath, f"{day}.jsonl")
    line = json.dumps({**idx, "doc": doc}, default=str)
    with open(path, "a") as f:
        f.write(line + "\n")
    return path


def history_scan(dirpath) -> list[dict]:
    """Every ledger entry under ``DIR`` (all ``*.jsonl`` partitions),
    sorted by capture time.  Malformed lines are skipped (a killed run
    may have torn its last append; the usable prefix is the ledger)."""
    dirpath = os.fspath(dirpath)
    entries: list[dict] = []
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return []
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        try:
            with open(os.path.join(dirpath, name)) as f:
                for raw in f:
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        obj = json.loads(raw)
                    except ValueError:
                        continue
                    if isinstance(obj, dict) and str(
                            obj.get("ledger", "")).startswith(
                            "acg-tpu-history"):
                        entries.append(obj)
        except OSError:
            continue
    entries.sort(key=lambda e: e.get("unix_time") or 0.0)
    return entries


def load_history_baseline(dirpath) -> tuple[dict, bool, int]:
    """The ``--baseline-from-history`` selection: the best-known USABLE
    value per case across every ledger entry.  Entries recording only
    the ``bench_backend_unavailable`` sentinel (the BENCH_r05
    stale-baseline trap) are skipped; returns ``(cases,
    all_unavailable, nentries)`` where ``all_unavailable`` is True when
    entries exist but none was usable."""
    from acg_tpu.perfmodel import UNAVAILABLE_METRIC
    entries = history_scan(dirpath)
    cases: dict = {}
    nsentinel = nother = 0
    for e in entries:
        case, value = e.get("case"), e.get("value")
        if (case == UNAVAILABLE_METRIC
                or str(case).startswith(UNAVAILABLE_METRIC + "|")):
            nsentinel += 1
            continue
        if (not case or not isinstance(value, (int, float))
                or value <= 0):
            # unusable for some OTHER reason (a failed run the ledger
            # deliberately records, an uncased entry): must NOT trigger
            # the backend-was-down diagnosis below
            nother += 1
            continue
        cases[case] = max(cases.get(case, float("-inf")), float(value))
    # the re-baseline refusal claims the backend/tunnel was down: only
    # say so when EVERY unusable entry is the sentinel
    all_unavailable = (bool(entries) and not cases
                       and nsentinel > 0 and nother == 0)
    return cases, all_unavailable, len(entries)
