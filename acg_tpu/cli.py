"""The ``acg-tpu`` CLI driver.

Rebuilds the reference's driver ``cuda/acg-cuda.c`` (SURVEY.md component
#22): the same 11-stage pipeline -- read matrix, partition, scatter, build
right-hand side (optionally a manufactured solution verified against an
independent host SpMV), initialise the device solver, dispatch on
``--solver``, print the statistics block to stderr, and write the solution
(and optionally the part-to-part communication matrix) as Matrix Market.

Flag names follow ``cuda/acg-cuda.c:321-377``.  Differences, by design:
  * ``--comm none|xla|dma`` replaces ``none|mpi|nccl|nvshmem``: on TPU the
    transport choice is XLA collectives vs Pallas remote DMA; ``mpi``,
    ``nccl`` and ``nvshmem`` are accepted as aliases of ``xla``/``dma`` for
    drop-in script compatibility.
  * ``--nparts`` selects the mesh size (the reference gets this from the
    MPI launcher).
  * ``--dtype f64|f32|bf16`` exposes the TPU precision trade-off; ``f64``
    reproduces the reference's strictly-double semantics.
  * solver names ``acg-device`` / ``acg-pipelined-device`` are accepted and
    run the same compiled whole-solve programs as ``acg`` /
    ``acg-pipelined``: XLA's execution model is already the monolithic
    device-initiated variant (SURVEY.md section 7).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="acg-tpu",
        description="TPU-accelerated conjugate gradient solver for symmetric "
                    "positive definite linear systems Ax=b.",
        epilog="Report bugs to the acg-tpu repository.")
    p.add_argument("A", help="matrix in Matrix Market format (.mtx, .mtx.gz, "
                             "binary), or a generator spec "
                             "gen:poisson2d:N | gen:poisson3d:N | "
                             "gen:irregular:N[:AVGDEG] -- synthesized "
                             "in-process; large Poisson specs assemble "
                             "directly on device (no file, no host matrix)")
    p.add_argument("b", nargs="?", default=None, help="right-hand side vector (default: ones)")
    p.add_argument("x0", nargs="?", default=None, help="initial guess (default: zeros)")
    p.add_argument("--solver", default="acg",
                   choices=["acg", "acg-pipelined", "acg-device",
                            "acg-pipelined-device", "host", "host-native",
                            "petsc"],
                   help="solver variant (default: acg); host = numpy "
                        "reference oracle, host-native = C++ core oracle "
                        "(native/src/cg.cpp)")
    p.add_argument("--algorithm", default="auto", metavar="ALG",
                   help="CG recurrence: 'classic' | 'pipelined' "
                        "(Ghysels-Vanroose, = --solver acg-pipelined) | "
                        "'sstep:S' (communication-avoiding s-step CG: "
                        "ONE fused Gram allreduce per S iterations, "
                        "monomial basis below S=4, Chebyshev at S>=4) | "
                        "'pipelined:L' (deep-pipelined p(l)-CG: ONE "
                        "fused allreduce per iteration consumed L "
                        "iterations later -- L reduction latencies "
                        "hidden behind L SpMVs; restarted on the "
                        "method's square-root breakdown).  'auto' "
                        "follows --solver.  The CA recurrences ride "
                        "the single-device, sharded gen-direct and "
                        "distributed tiers, run unpreconditioned over "
                        "f32/f64 vectors, and compose with telemetry/"
                        "faults/recovery (+ the health audit for "
                        "sstep)")
    p.add_argument("--comm", default="xla",
                   choices=["none", "xla", "dma", "mpi", "nccl", "nvshmem"],
                   help="halo transport: xla collectives or pallas dma "
                        "(mpi/nccl alias xla, nvshmem aliases dma)")
    p.add_argument("--nparts", type=int, default=0,
                   help="mesh size / number of subdomains (default: all devices; "
                        "0 with --comm none means 1)")
    p.add_argument("--partition", metavar="FILE", default=None,
                   help="read row partition vector from FILE (mtxpartition "
                        "output).  Under --distributed-read the partition "
                        "must instead be applied OFFLINE (mtx2bin --expand "
                        "--partition VECFILE, which permutes the matrix "
                        "part-contiguous) and FILE names the tiny "
                        ".bounds.mtx sidecar it writes (auto-detected "
                        "next to the matrix when omitted) -- reading a "
                        "full vector per controller would break the "
                        "O(local nnz) ingest contract")
    p.add_argument("--partition-method", default="auto",
                   choices=["auto", "graph", "band"],
                   help="row partition strategy: graph = edge-cut "
                        "minimisation (METIS/bisection), band = contiguous "
                        "nnz-balanced ranges (keeps banded matrices in "
                        "gather-free DIA form on TPU); auto picks band for "
                        "banded matrices (default)")
    p.add_argument("--partition-binary", "--binary-partition",
                   action="store_true", dest="partition_binary",
                   help="partition vector file is in binary Matrix Market format")
    p.add_argument("--binary", action="store_true",
                   help="matrix/vector files are in binary Matrix Market format")
    p.add_argument("--gzip", "--gunzip", "--ungzip", action="store_true",
                   dest="gzip",
                   help="accepted for drop-in compatibility; gzip input is "
                        "auto-detected from the magic bytes regardless")
    # default=False: these register before their store_true partners,
    # and argparse keeps the FIRST registered default for a shared dest
    p.add_argument("--no-manufactured-solution",
                   dest="manufactured_solution", action="store_false",
                   default=False, help=argparse.SUPPRESS)
    p.add_argument("--no-output-comm-matrix",
                   dest="output_comm_matrix", action="store_false",
                   default=False, help=argparse.SUPPRESS)
    p.add_argument("--max-iterations", type=int, default=100, metavar="N",
                   help="maximum number of iterations (default: 100)")
    p.add_argument("--residual-atol", type=float, default=0.0, metavar="TOL",
                   help="stop when the residual norm is below TOL")
    p.add_argument("--residual-rtol", type=float, default=1e-9, metavar="TOL",
                   help="stop when the relative residual is below TOL (default: 1e-9)")
    p.add_argument("--diff-atol", type=float, default=0.0, metavar="TOL",
                   help="stop when the difference in solution iterates is below TOL")
    p.add_argument("--diff-rtol", type=float, default=0.0, metavar="TOL",
                   help="stop on relative difference in solution iterates")
    p.add_argument("--epsilon", type=float, default=0.0,
                   help="diagonal shift: solve (A + epsilon*I)x = b")
    p.add_argument("--warmup", type=int, default=10, metavar="N",
                   help="warmup solves before the timed solve (default: 10)")
    p.add_argument("--manufactured-solution", action="store_true",
                   help="use a random unit-norm solution and b = A*xsol; "
                        "report error norms")
    p.add_argument("--output-comm-matrix", action="store_true",
                   help="write the part-to-part communication volume matrix "
                        "to stdout as Matrix Market")
    p.add_argument("--dtype", default="f64",
                   choices=["f64", "f32", "mixed", "bf16"],
                   help="device precision (default: f64).  'mixed' = bf16 "
                        "matrix storage + f32 vectors/scalars: halves "
                        "matrix HBM traffic, and is arithmetic-identical "
                        "to f32 when the entries are bf16-representable "
                        "(Poisson stencils).  'bf16' stores vectors in "
                        "bf16 too (half traffic everywhere, f32 scalars) "
                        "but caps convergence at condition numbers "
                        "~1/u_bf16 ~ 500 -- combine with --replace-every "
                        "for f32-class residuals at any conditioning, or "
                        "use alone for well-conditioned systems / "
                        "throughput measurement")
    p.add_argument("--kernels", default="auto",
                   choices=["auto", "xla", "pallas", "fused"],
                   help="hot-loop kernel tier: xla = compiler-fused ops, "
                        "pallas = hand-written single-x-pass DIA SpMV "
                        "(the reference's cg-kernels-cuda.cu tier; vector "
                        "updates stay in XLA -- see BASELINE.md); fused = "
                        "single-device: the two-phase whole-iteration "
                        "kernel pair (classic CG on single-window DIA "
                        "shapes); with --nparts: the interior/border "
                        "OVERLAPPED iteration (halo exchange in flight "
                        "behind the interior SpMV; classic + pipelined); "
                        "auto picks pallas on TPU hardware for DIA "
                        "matrices and DIA local blocks of the multi-part "
                        "path")
    p.add_argument("--spmv-format", default="auto",
                   choices=["auto", "dia", "ell", "coo"],
                   help="force the device sparse format for the "
                        "single-device path (the role of the reference's "
                        "--cusparse-spmv-alg algorithm selector); auto "
                        "picks by sparsity structure")
    p.add_argument("--replace-every", type=int, default=0, metavar="K",
                   help="with --dtype bf16: periodic f32 residual "
                        "replacement every K iterations (classic CG; "
                        "single-device AND distributed/mesh paths) -- "
                        "the sound-bf16 contract: f32-class residuals at "
                        "~2%% overhead (K=50 measured at flagship "
                        "conditioning; 0 = off)")
    p.add_argument("--precond", default="none", metavar="KIND",
                   help="preconditioner (acg_tpu.precond): none | "
                        "jacobi (inverse-diagonal scaling, zero extra "
                        "communication) | bjacobi[:BS] (dense Cholesky "
                        "of the BSxBS local diagonal blocks, batched "
                        "triangular solves, no halo traffic; default "
                        "BS 32) | cheby:K (degree-K Chebyshev "
                        "polynomial -- K extra SpMVs per iteration "
                        "riding the tier's own SpMV + halo machinery, "
                        "lambda_max from a power iteration at setup).  "
                        "Turns the classic/pipelined solvers into PCG / "
                        "pipelined-PCG on every device tier; 'none' "
                        "compiles byte-identical unpreconditioned "
                        "programs (default)")
    p.add_argument("--operator", default="none", metavar="SPEC",
                   help="matrix-free operator tier (acg_tpu.ops."
                        "operator): solve with A as a jitted APPLY "
                        "instead of stored planes -- zero matrix HBM "
                        "traffic per iteration, trajectories bitwise-"
                        "equal to the assembled-DIA tier on the "
                        "classic/sstep/jacobi/batched/dist tiers "
                        "(FMA-reassociation-level on the apply-"
                        "chaining pipelined/cheby/ABFT setups).  'stencil' "
                        "derives the built-in stencil from the gen: "
                        "matrix spec (+ --aniso for the variable-"
                        "coefficient family); "
                        "stencil:poisson1d|poisson2d|poisson3d:N and "
                        "stencil:aniso2d:N:EPS name it explicitly "
                        "(validated against the matrix being solved); "
                        "user:NAME runs an operator registered via "
                        "register_operator (in-process callers).  "
                        "Rides every device tier -- classic/pipelined, "
                        "sstep:S / pipelined:L, --nrhs (single device), "
                        "--precond jacobi (analytic diagonal) / "
                        "cheby:K, --abft (checksum through the apply), "
                        "and the --nparts mesh incl. --kernels fused "
                        "(interior/border split applied to the stencil "
                        "apply) and --comm dma.  'none' (default) "
                        "leaves every dispatched program byte-"
                        "identical to the assembled build")
    p.add_argument("--aniso", type=float, default=None, metavar="EPS",
                   help="with gen:poisson2d:N: generate the ANISOTROPIC "
                        "(stretched-grid) Poisson family instead -- "
                        "y-spacings graded by stretch factor EPS in "
                        "(0, 1]; the diagonal then varies by ~1/EPS, "
                        "the ill-conditioned SPD family where "
                        "--precond measurably cuts iterations")
    p.add_argument("--audit-every", type=int, default=0, metavar="K",
                   help="numerical-health tier (acg_tpu.health): every "
                        "K iterations the compiled solve loop "
                        "recomputes the TRUE residual b - Ax through "
                        "the tier's own SpMV/halo machinery and "
                        "carries the relative gap ||r_true - r_rec||/"
                        "||b|| -- the drift pipelined CG trades for "
                        "hidden latency.  The gap lands in a 'health:' "
                        "stats section, the acg_health_* metrics, and "
                        "(with --convergence-log) a 'gap' column in "
                        "the trace.  0 (default) compiles the "
                        "byte-identical unaudited programs")
    p.add_argument("--gap-threshold", type=float, default=0.0,
                   metavar="G",
                   help="with --audit-every: a relative gap above G "
                        "emits a structured accuracy_degraded event "
                        "and drives --on-gap (default 0: record-only)")
    p.add_argument("--on-gap", default="warn",
                   choices=["warn", "replace", "abort"],
                   help="what a gap past --gap-threshold does: warn = "
                        "event only; replace = exit the loop through "
                        "the breakdown path and let the recovery "
                        "driver restart from the recomputed true "
                        "residual (a residual-replacement restart; "
                        "restarts bounded by --max-restarts); abort = "
                        "fail the solve (default: warn)")
    p.add_argument("--stall-window", type=int, default=0, metavar="N",
                   help="device-side stagnation detector: N "
                        "consecutive iterations without residual "
                        "decrease exit through the breakdown path "
                        "(with --recover: bounded restarts; default: "
                        "off).  Arms the dot-product sign-anomaly "
                        "guards too")
    p.add_argument("--abft", action="store_true",
                   help="survivability tier (acg_tpu.checkpoint/health): "
                        "arm the Huang-Abraham CHECKSUM-PROTECTED SpMV "
                        "-- the column checksum c = A^T 1 is computed "
                        "once through the tier's own SpMV and every "
                        "--audit-every iterations the in-loop test "
                        "compares sum(A p) against (c, p) (ONE fused "
                        "reduction on the mesh tiers), so silent "
                        "bit-level corruption of the SpMV (sdc:flip) "
                        "that never trips a non-finite guard is "
                        "detected ON DEVICE and routed into the "
                        "breakdown -> rollback/recovery path.  Needs "
                        "--audit-every K")
    p.add_argument("--abft-threshold", type=float, default=0.0,
                   metavar="T",
                   help="with --abft: relative checksum-mismatch trip "
                        "level (default 0 = a dtype/size-derived bound, "
                        "64*sqrt(n)*eps -- generous rounding headroom, "
                        "orders of magnitude below one flipped "
                        "element's signature)")
    p.add_argument("--ckpt", metavar="FILE", default=None,
                   help="survivability tier (acg_tpu.checkpoint): write "
                        "SOLVER-STATE SNAPSHOTS -- the full loop carry "
                        "(x, r, p, pipelined extras, preconditioned "
                        "rr), iteration, tolerances, fault residue and "
                        "telemetry tail -- to FILE by atomic rename "
                        "with a checksummed header, every --ckpt-every "
                        "iterations.  The solve runs as host chunks of "
                        "the UNCHANGED recurrence (iteration-identical "
                        "to an uninterrupted run); on the dist tier "
                        "every rank's state commits under one agreed "
                        "sequence number.  A detected breakdown rolls "
                        "back to the last snapshot before spending the "
                        "restart budget; a killed process resumes via "
                        "--resume.  Distinct from the multi-controller "
                        "STAGE SYNC barriers (--err-timeout), which "
                        "agree on status codes and store nothing")
    p.add_argument("--ckpt-every", type=int, default=0, metavar="K",
                   help="with --ckpt: snapshot period in iterations "
                        "(also the host chunk length; exactly one of "
                        "--ckpt-every/--ckpt-secs is required)")
    p.add_argument("--ckpt-secs", type=float, default=0.0, metavar="S",
                   help="with --ckpt: WALL-CLOCK snapshot cadence -- "
                        "the chunk drivers size each dispatch from the "
                        "measured seconds/iteration so one snapshot "
                        "commits about every S seconds of solve time "
                        "(slow iterations no longer stretch the loss "
                        "window the way a fixed --ckpt-every K does); "
                        "snapshot time bills to the ckpt phase as "
                        "usual.  Mutually exclusive with --ckpt-every")
    p.add_argument("--resume", metavar="FILE", default=None,
                   help="reconstruct the solver state from a --ckpt "
                        "snapshot and CONTINUE the solve to the "
                        "original tolerance (the absolute target is "
                        "stored, so rtol is never re-baselined); "
                        "refuses snapshots from a different tier/"
                        "algorithm/preconditioner/size/right-hand side "
                        "or with a corrupted header.  Total iterations "
                        "(pre-crash + post-resume) match an "
                        "uninterrupted run.  Combine with --ckpt to "
                        "keep snapshotting after the resume")
    p.add_argument("--resume-repartition", action="store_true",
                   help="with --resume: accept a snapshot from a "
                        "DIFFERENT partition count or solver tier "
                        "(dist <-> single-device <-> host oracle) -- "
                        "the carry vectors are reassembled into global "
                        "row order through the snapshot's row-"
                        "permutation sidecar, re-sliced onto THIS "
                        "run's partition (halo plans and "
                        "preconditioner state rebuild at setup), and "
                        "the solve continues to the ORIGINAL "
                        "tolerance.  This is how a solve survives a "
                        "lost chip: resume on the survivor mesh with "
                        "fewer --nparts (the --supervise mode does "
                        "this automatically).  Algorithm/dtype/"
                        "preconditioner/right-hand-side mismatches "
                        "still refuse; a corrupted permutation "
                        "sidecar refuses")
    p.add_argument("--heartbeat", type=float, default=0.0,
                   metavar="SECONDS",
                   help="multi-controller dead-peer detection DURING "
                        "the solve collective (erragree."
                        "DeadlineHeartbeat): each controller bumps a "
                        "coordination-service key from a daemon thread "
                        "and declares a peer dead after SECONDS of "
                        "silence, tearing down with the peer-lost exit "
                        "code so the supervisor can relaunch with "
                        "--resume -- the stage-sync watchdog "
                        "(--err-timeout) cannot see a peer that dies "
                        "INSIDE a collective (default: off)")
    p.add_argument("--supervise", action="store_true",
                   help="elastic-recovery tier (acg_tpu.supervisor): "
                        "run the solve as a SUPERVISED CHILD process "
                        "and watch the exit-code contract (see "
                        "--buildinfo): a crash (rc 94), a lost peer "
                        "(rc 86/97), a signal death or a failed solve "
                        "relaunches the child with --resume from the "
                        "last committed snapshot -- shrinking --nparts "
                        "onto the survivor mesh with "
                        "--resume-repartition when a peer was lost "
                        "(--shrink) -- under a bounded relaunch budget "
                        "with exponential backoff.  Needs --ckpt FILE "
                        "with a cadence; drift (rc 7) and SLO (rc 8) "
                        "verdicts pass through.  Relaunch decisions "
                        "land as acg_recovery_* metrics, a recovery: "
                        "stats section, and the status document's "
                        "degraded key")
    p.add_argument("--relaunch-budget", type=int, default=3, metavar="N",
                   help="with --supervise: relaunches granted before "
                        "giving up with exit 95 (default: 3)")
    p.add_argument("--relaunch-backoff", type=float, default=1.0,
                   metavar="SECONDS",
                   help="with --supervise: sleep SECONDS * 2^(n-1) "
                        "before the n-th relaunch (default: 1)")
    p.add_argument("--shrink", default="peer-lost",
                   choices=["never", "peer-lost", "any"],
                   help="with --supervise: which failures shrink the "
                        "mesh on relaunch (halving --nparts down to "
                        "--min-parts, resuming with "
                        "--resume-repartition): peer-lost = only dead-"
                        "peer teardowns (rc 86/97; default), any = "
                        "every relaunchable failure (lets a single-"
                        "host crash demonstrate the elastic ladder), "
                        "never = always relaunch on the same mesh")
    p.add_argument("--min-parts", type=int, default=1, metavar="M",
                   help="with --supervise: never shrink below M parts "
                        "(default: 1)")
    p.add_argument("--grow-after", type=int, default=0, metavar="N",
                   help="with --serve --supervise: grow-on-recovery -- "
                        "a SHRUNKEN daemon (crash relaunch halved "
                        "--nparts) that stays healthy for N served "
                        "requests is relaunched back toward the "
                        "original mesh width (doubling --nparts, with "
                        "--resume-repartition), counted by "
                        "acg_recovery_regrows_total (default: 0 = "
                        "never grow back)")
    p.add_argument("--serve", action="store_true",
                   help="solver-service tier (acg_tpu.serve): run a "
                        "LONG-LIVED daemon that owns the mesh and "
                        "answers POST /solve over HTTP (JSON in/out; "
                        "GET /status, /metrics, /healthz; POST "
                        "/shutdown).  The positional matrix is "
                        "preloaded into the OPERATOR CACHE; each "
                        "request names its own gen: operator, b, and "
                        "solver knobs.  Repeated request shapes hit "
                        "the operator + compiled-program caches (zero "
                        "ingest, zero compile -- acg_serve_cache_*), "
                        "compatible queued requests coalesce into one "
                        "batched multi-RHS solve (bitwise-equal to "
                        "single service), admission control sheds with "
                        "typed 429/503 responses and DOWNGRADES before "
                        "refusing as the --slo error budget burns, and "
                        "a failed request is answered with a typed "
                        "error -- never a dead daemon.  --supervise "
                        "wraps it in the relaunch/shrink/grow ladder "
                        "(warm cache restore from --ckpt serve state); "
                        "--chaos SEED[:N] fires seeded fault schedules "
                        "at the LIVE daemon with per-request answer "
                        "verification (exit 96 on wrong-answer-green)")
    p.add_argument("--serve-port", type=int, default=0, metavar="PORT",
                   help="with --serve: bind PORT (default 0 = "
                        "OS-assigned, printed to stderr)")
    p.add_argument("--serve-queue-depth", type=int, default=16,
                   metavar="N",
                   help="with --serve: bounded request queue depth; "
                        "an arrival past it is shed with a typed 429 "
                        "(default: 16)")
    p.add_argument("--serve-coalesce", type=int, default=8, metavar="B",
                   help="with --serve: coalesce up to B compatible "
                        "queued requests into one batched multi-RHS "
                        "solve (1 disables; default: 8)")
    p.add_argument("--serve-deadline", type=float, default=60.0,
                   metavar="SECONDS",
                   help="with --serve: default per-request deadline "
                        "(a request may set its own 'timeout'); an "
                        "expired request is answered with a typed 504 "
                        "(default: 60)")
    p.add_argument("--serve-faults", action="store_true",
                   help="with --serve: honour per-request 'fault' "
                        "fields (crash / slow:S / device fault specs) "
                        "-- the chaos campaign's hook; NEVER arm on a "
                        "production service")
    p.add_argument("--access-log", metavar="FILE", default=None,
                   help="with --serve: append one acg-tpu-access/1 "
                        "JSONL row per request (atomic line writes): "
                        "request_id, outcome, per-stage seconds "
                        "(admit/queue-wait/coalesce/cache/compile/"
                        "solve/demux/respond), cache + coalesce + "
                        "degrade + plan provenance, batch id/width "
                        "with per-RHS solve attribution.  "
                        "scripts/access_report.py renders the per-"
                        "stage p50/p95/p99 table and tail "
                        "decomposition; scripts/check_access_log.py "
                        "validates the ledger")
    p.add_argument("--chaos", metavar="SEED[:N]", default=None,
                   help="chaos campaign (acg_tpu.supervisor): generate "
                        "N (default 20) seeded randomized fault "
                        "schedules over the existing fault sites "
                        "(crash:exit, sdc:flip when --abft is armed, "
                        "spmv/halo/dot corruption, peer:dead under "
                        "--multihost, solve:slow under --soak), run "
                        "each through the supervisor, independently "
                        "VERIFY every green run's true residual "
                        "against a host-side rebuild of the matrix, "
                        "and record per-schedule verdicts (converged / "
                        "agreed-abort / WRONG-ANSWER) to stderr and "
                        "the --history ledger.  Exit 96 if ANY "
                        "schedule converged to a wrong answer -- the "
                        "acceptance bar is zero wrong-answer-green")
    p.add_argument("--nrhs", type=int, default=0, metavar="B",
                   help="batched multi-RHS tier (acg_tpu.solvers."
                        "batched): solve B right-hand sides against "
                        "the ONE ingested matrix in a single batched "
                        "program -- one multi-vector SpMV per "
                        "iteration (matrix HBM traffic amortized B-"
                        "fold), ALL per-RHS dots fused into B-wide "
                        "reductions (on the mesh: collective count "
                        "INVARIANT in B), per-RHS convergence masks "
                        "(converged columns freeze, the loop runs to "
                        "the slowest RHS).  b may be an n x B dense "
                        "array file; without a b file, B seeded random "
                        "unit-norm columns (--seed); with "
                        "--manufactured-solution, B manufactured "
                        "columns.  Per-RHS evidence lands in a "
                        "'batch:' stats section, the per-RHS residual "
                        "ring (--convergence-log), the status "
                        "document (ETA keyed to the slowest "
                        "unconverged RHS) and per-RHS soak "
                        "percentiles.  B=1 (or flag absent) runs the "
                        "byte-identical single-RHS programs")
    p.add_argument("--block-cg", action="store_true",
                   help="with --nrhs B: the TRUE block-CG recurrence "
                        "instead of the masked batched one -- ONE "
                        "shared Krylov block, B x B Gram solves with "
                        "rank deflation on breakdown; converges in "
                        "measurably fewer total iterations than B "
                        "independent solves on ill-conditioned "
                        "families (--aniso).  Single-device tier "
                        "(--nparts 1 / --comm none)")
    p.add_argument("--precise-dots", action="store_true",
                   help="compensated (double-float) dot products for the "
                        "CG scalars; lets f32 storage converge past the "
                        "~1e-6 relative-residual stall")
    p.add_argument("--refine", action="store_true",
                   help="mixed-precision iterative refinement: f64 outer "
                        "residual on host, --dtype inner solves on device; "
                        "reaches f64 tolerances at f32 device speed")
    p.add_argument("--refine-rtol", type=float, default=1e-5, metavar="TOL",
                   help="relative tolerance of each inner refinement solve "
                        "(default: 1e-5)")
    p.add_argument("--refine-inner-maxits", type=int, default=None,
                   metavar="N",
                   help="cap each inner refinement solve at N iterations "
                        "(bounds one device program's runtime -- needed "
                        "at pod-filling sizes where a watchdog kills "
                        "long programs; default: the remaining "
                        "--max-iterations budget)")
    p.add_argument("--seed", type=int, default=42,
                   help="random seed for partitioning and manufactured solutions")
    p.add_argument("--numfmt", default="%.17g", metavar="FMT",
                   help="printf-style format for numeric output")
    p.add_argument("--multihost", action="store_true",
                   help="initialise the JAX multi-controller runtime before "
                        "solving (the MPI_Init stage); on TPU pods the "
                        "cluster layout is auto-detected, elsewhere pass "
                        "--coordinator/--num-processes/--process-id")
    p.add_argument("--coordinator", metavar="HOST:PORT", default=None,
                   help="multi-controller coordinator address "
                        "(implies --multihost)")
    p.add_argument("--num-processes", type=int, default=None, metavar="N",
                   help="total controller processes (with --coordinator)")
    p.add_argument("--process-id", type=int, default=None, metavar="I",
                   help="this controller's index (with --coordinator)")
    p.add_argument("--distributed-read", action="store_true",
                   help="pod-scale ingest: each controller RANGE-READS "
                        "only its own rows from a row-sorted full-"
                        "storage binary file (mtx2bin --expand output; "
                        "requires --binary) and builds only its own "
                        "subdomains -- I/O, host memory and "
                        "preprocessing are O(local nnz) per controller "
                        "(the role of the reference's root-read + "
                        "subgraph scatter, graph.c:1529-1897, without "
                        "the root).  Uses a contiguous equal-rows band "
                        "partition")
    p.add_argument("--recover", action="store_true",
                   help="arm breakdown detection + bounded restart "
                        "recovery in the device solve loops: non-finite "
                        "residuals / non-positive p^T A p exit the loop, "
                        "the solver restarts from the recomputed true "
                        "residual (--max-restarts, --restart-backoff), "
                        "falls back dma->xla halo transport, then the "
                        "host reference solver -- every event in the "
                        "stats block")
    p.add_argument("--max-restarts", type=int, default=2, metavar="N",
                   help="with --recover/--fault-inject: bounded restarts "
                        "per solve before falling back (default: 2)")
    p.add_argument("--restart-backoff", type=float, default=0.0,
                   metavar="SECONDS",
                   help="sleep SECONDS * 2^(n-1) before the n-th restart "
                        "(transient environmental faults get time to "
                        "clear; default: 0 -- numerical breakdowns "
                        "restart immediately)")
    p.add_argument("--fault-inject", metavar="SPEC", default=None,
                   help="arm the deterministic fault injector "
                        "(acg_tpu.faults): SITE:MODE[@ITER][:KEY=VAL] "
                        "-- e.g. spmv:nan@7, halo:inf@3:part=2, "
                        "dot:neg@5, peer:dead:proc=1, backend:hang:"
                        "secs=120.  Implies breakdown detection; "
                        "recovery knobs as with --recover")
    p.add_argument("--err-timeout", type=float, default=120.0,
                   metavar="SECONDS",
                   help="multi-controller error-agreement watchdog (the "
                        "STAGE SYNC barriers -- status agreement, not the "
                        "--ckpt state snapshots): how long to wait at a "
                        "stage-sync point for peers before concluding one "
                        "died and aborting (the acgerrmpi analog; default: "
                        "120).  Must exceed the worst-case arrival SKEW "
                        "between controllers at any sync point (not the "
                        "stage duration): e.g. a "
                        "replicated read of a large .mtx from a slow "
                        "filesystem can stagger 'ingest' arrivals by "
                        "minutes -- raise this accordingly or a healthy "
                        "but slow peer gets the pod aborted")
    p.add_argument("--convergence-log", metavar="FILE", default=None,
                   help="record per-iteration (rnrm2, alpha, beta, pAp) "
                        "in a device-side ring buffer riding the "
                        "compiled solve loop (fetched once with the "
                        "result -- zero extra host transfers per "
                        "iteration) and write it to FILE as JSONL: one "
                        "meta line (wrap/truncation marked), one record "
                        "per surviving iteration.  Window size: "
                        "--telemetry-window.  Render with "
                        "scripts/plot_convergence.py")
    p.add_argument("--telemetry-window", type=int, default=512,
                   metavar="N",
                   help="ring-buffer capacity (iterations) for "
                        "--convergence-log (default: 512; the trailing "
                        "N iterations survive a longer solve)")
    p.add_argument("--progress", type=int, default=0, metavar="K",
                   help="heartbeat: print the residual 2-norm to stderr "
                        "every K iterations FROM INSIDE the compiled "
                        "solve loop (jax.debug callback) -- the "
                        "liveness signal for long solves (default: off)")
    p.add_argument("--stats-json", metavar="FILE", default=None,
                   help="write a schema-versioned machine-readable twin "
                        "of the stats block to FILE: run manifest "
                        "(backend, mesh, kernel tier, comm transport, "
                        "jax versions, matrix id, partition/halo "
                        "sizes), per-op counters, phase timings, "
                        "timestamped resilience/fault events, the "
                        "convergence trace, and on multihost runs the "
                        "cross-rank min/median/max + imbalance "
                        "aggregation")
    p.add_argument("--soak", type=int, default=0, metavar="N",
                   help="service-soak mode: run N repeated solves of "
                        "the same system (first one carries --warmup), "
                        "feed every solve into the process-wide "
                        "metrics registry, report p50/p95/p99 solve "
                        "latency + iterations from its histograms in a "
                        "'soak:' stats section, and arm an EWMA "
                        "latency-drift detector (see --fail-on-drift). "
                        " Single-controller only")
    p.add_argument("--fail-on-drift", type=float, default=None,
                   metavar="PCT",
                   help="with --soak: exit 7 when EWMA solve latency "
                        "drifts more than PCT percent above the "
                        "baseline window's median (default: warn-only "
                        "at 50%%)")
    p.add_argument("--metrics-file", metavar="FILE", default=None,
                   help="write the service-metrics registry "
                        "(acg_tpu.metrics: solve/iteration counters, "
                        "latency + phase histograms, halo/psum byte "
                        "counters, RSS/device-memory gauges) to FILE "
                        "in Prometheus text format -- atomic rename, "
                        "flushed on exit and on SIGTERM (the "
                        "node-exporter textfile-collector contract)")
    p.add_argument("--metrics-port", type=int, default=0, metavar="PORT",
                   help="serve GET /metrics (Prometheus text format) "
                        "on PORT from a daemon thread for the "
                        "process's lifetime (default: off)")
    p.add_argument("--status-port", type=int, default=0, metavar="PORT",
                   help="live in-flight status: serve GET /status (an "
                        "acg-tpu-status/1 JSON document: phase, "
                        "iteration, residual trail, iterations/sec, "
                        "ETA from the Lanczos kappa CG-bound falling "
                        "back to the measured rate, per-part "
                        "imbalance, last events, soak progress) on "
                        "PORT from a daemon thread; the same port "
                        "also answers /metrics, so one endpoint can "
                        "serve both planes (default: off)")
    p.add_argument("--status-file", metavar="FILE", default=None,
                   help="write the acg-tpu-status/1 document to FILE "
                        "(atomic rename -- a poller never reads torn "
                        "JSON), refreshed on every status update at "
                        "most every 0.2 s and finalised on exit -- "
                        "the file-based twin of --status-port for "
                        "pods without a reachable port")
    p.add_argument("--history", metavar="DIR", default=None,
                   help="run-history ledger: append this solve's "
                        "--stats-json document to a date-partitioned "
                        "JSONL ledger under DIR (one acg-tpu-history/1 "
                        "index line per solve -- matrix, tier, "
                        "precond, dtype, latency, iterations, schema "
                        "-- carrying the full document).  Render "
                        "trends with scripts/history_report.py; "
                        "bench_diff.py/check_regression accept DIR as "
                        "a baseline (--baseline-from-history), "
                        "picking the best USABLE prior capture and "
                        "skipping bench_backend_unavailable entries")
    p.add_argument("--slo", metavar="SPEC", default=None,
                   help="declare per-solve service-level objectives "
                        "as latency=SECONDS,iters=N,gap=G (any "
                        "subset): targets land on the metrics "
                        "registry as acg_slo_target, every completed "
                        "solve is judged (breaches bump "
                        "acg_slo_breaches_total, refresh the "
                        "cumulative acg_slo_burn_ratio error-budget "
                        "gauge, and emit slo-breach events into the "
                        "telemetry/timeline stream), and the verdict "
                        "lands in an 'slo:' stats section")
    p.add_argument("--fail-on-slo", action="store_true",
                   help="with --slo: exit 8 when any declared "
                        "objective breached during the run (the "
                        "--fail-on-drift design; works for single "
                        "solves and --soak runs alike)")
    p.add_argument("--explain", action="store_true",
                   help="performance-observability report instead of a "
                        "normal solve: lower + compile the classic, "
                        "pipelined and distributed whole-solve programs "
                        "for this system, extract the compiler's own "
                        "cost_analysis/memory_analysis (the costmodel:/"
                        "memory: stats sections and their --stats-json "
                        "twin), build the static communication ledger "
                        "(per-neighbour halo bytes, psum counts, ICI-hop "
                        "estimates), and print a per-tier roofline "
                        "verdict -- predicted vs. measured iteration "
                        "time against the probed bandwidth and a bound "
                        "classification (compute/HBM/comm/dispatch).  "
                        "Degrades gracefully where the analysis is "
                        "unsupported on the running jax version/backend")
    p.add_argument("--commbench", nargs="?", const="-", default=None,
                   metavar="FILE",
                   help="communication observatory: run the collective "
                        "microbenchmark suite over this run's mesh "
                        "(psum/all_reduce scalar latency, all_to_all + "
                        "collective_permute bandwidth sweeps, per-edge "
                        "one-sided halo_dma put/wait timing) plus a "
                        "measured SpMV/halo/reduction segment "
                        "decomposition of this case, fit an alpha-beta "
                        "model per collective kind, and write the "
                        "acg-tpu-commbench/1 calibration document to "
                        "FILE ('-' or omitted = stdout).  Standalone "
                        "mode, or combined with --explain to calibrate "
                        "the roofline verdict live")
    p.add_argument("--calibration", metavar="FILE", default=None,
                   help="a saved --commbench document: --explain prices "
                        "comm from its fitted alpha-beta model instead "
                        "of ring-hop estimates and reports predicted-vs-"
                        "measured with calibration provenance; on a "
                        "normal solve the calibration id is recorded in "
                        "the --stats-json manifest and convergence-log "
                        "meta line (bench_diff keys differently-"
                        "calibrated captures apart)")
    p.add_argument("--plan", nargs="?", const="-", default=None,
                   metavar="FILE",
                   help="decision observatory: write the ranked "
                        "acg-tpu-plan/1 document (every candidate "
                        "program priced as predicted seconds-per-solve "
                        "from the perfmodel HBM roofline, the "
                        "--calibration alpha-beta comm fits over each "
                        "recurrence's reduction schedule, and the "
                        "Lanczos-kappa CG iteration bound; typed "
                        "refusal reasons for pruned cells) to FILE "
                        "('-' or omitted = stdout).  With --explain: "
                        "print the ranked table WITHOUT solving; with "
                        "--autotune: record the document the decision "
                        "came from")
    p.add_argument("--autotune", action="store_true",
                   help="plan the candidate program space, verify the "
                        "top-2 plans by short timed probes, and "
                        "dispatch the winner instead of the flag-"
                        "selected program (S / L / cheby degree chosen "
                        "numerically).  The decision and its plan-vs-"
                        "actual row (predicted vs measured s/solve, "
                        "misprediction ratio) land in the 'plan:' "
                        "stats section, the --history ledger (where "
                        "later planned runs consult them to self-"
                        "correct the model's constants) and the "
                        "acg_plan_* metric families")
    p.add_argument("--no-probe-cache", action="store_true",
                   help="ignore the on-disk backend-keyed triad-probe "
                        "sidecar (ACG_TPU_PROBE_CACHE / "
                        "~/.cache/acg-tpu/probe_cache.json) and "
                        "re-measure HBM bandwidth")
    p.add_argument("--profile-ops", nargs="?", const=10, type=int,
                   default=None, metavar="REPS",
                   help="fill the stats block's per-op seconds/GB/s by "
                        "replaying each op class standalone on device "
                        "(best of REPS calls, default 10 -- min rides out "
                        "shared-chip contention) -- the reference's "
                        "ACG_ENABLE_PROFILING tier")
    p.add_argument("--trace", metavar="DIR", default=None,
                   help="write a jax.profiler trace of the solve to DIR "
                        "(the reference's nsys-trace tier; view with "
                        "xprof).  The capture is also ANALYZED after the "
                        "solve: measured per-op-class device seconds, "
                        "overlap efficiency and straggler attribution "
                        "land in the 'tracing:' stats section, and "
                        "measured seconds replace the --profile-ops "
                        "replay estimates where the capture resolves an "
                        "op class")
    p.add_argument("--timeline", metavar="FILE", default=None,
                   help="write a cross-rank span timeline of this run "
                        "as Chrome trace-event JSON (one pid per part; "
                        "load in Perfetto / chrome://tracing).  Spans "
                        "come from the pipeline phases, checkpoint "
                        "chunk boundaries and telemetry events; "
                        "multi-controller runs gather spans over the "
                        "erragree KV plumbing with barrier-timestamp "
                        "clock alignment.  With --serve this is the "
                        "SERVICE timeline instead: the daemon records "
                        "for its whole lifetime -- one worker row of "
                        "batch solve spans plus one lane per "
                        "in-flight request window -- and exports at "
                        "shutdown")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="do not write the solution vector to stdout")
    p.add_argument("-o", "--output", metavar="FILE", default=None,
                   help="write the solution to FILE instead of stdout.  "
                        "Under --distributed-read the write is "
                        "DISTRIBUTED: each controller range-writes its "
                        "owned row windows of a binary array vector "
                        "directly (no full-vector gather on any "
                        "controller -- the mtxfile_fwrite_mpi_double "
                        "role, mtxfile.h:1087), the primary writes only "
                        "the header.  Rows are in the matrix's on-disk "
                        "ordering (permuted inputs stay permuted; the "
                        ".perm.mtx sidecar maps back)")
    p.add_argument("-v", "--verbose", action="count", default=0,
                   help="print stage timings to stderr")
    p.add_argument("--version", action="version", version="acg-tpu 0.1.0")
    p.add_argument("--buildinfo", action="store_true",
                   help="print the runtime feature matrix (the role of "
                        "the reference's CMake ACG_HAVE_* configuration) "
                        "and exit")
    return p


def _buildinfo(out) -> int:
    import jax
    import jaxlib

    from acg_tpu import _native, __version__
    from acg_tpu._platform import honour_jax_platforms
    from acg_tpu.partition import metis_available

    honour_jax_platforms()

    plat = "unavailable"
    try:
        devs = jax.devices()
        plat = f"{devs[0].platform} x{len(devs)} ({devs[0].device_kind})"
    except Exception as e:  # noqa: BLE001 -- report, don't crash
        plat = f"unavailable ({type(e).__name__})"
    from acg_tpu.telemetry import CONVERGENCE_SCHEMA, STATS_SCHEMA

    rows = [
        ("acg-tpu", __version__),
        ("jax", jax.__version__),
        ("jaxlib", jaxlib.__version__),
        ("backend", plat),
        ("native core (libacg_core)",
         "yes" if _native.available() else "no (numpy fallbacks)"),
        ("libmetis", "yes" if metis_available() else
         "no (built-in bisection fallback)"),
        ("float64", "emulated on TPU (use --refine / --precise-dots)"),
        ("telemetry", f"--convergence-log (in-loop ring buffer, "
         f"{CONVERGENCE_SCHEMA}), --progress (in-loop heartbeat), "
         f"--stats-json ({STATS_SCHEMA}, phase timings + cross-rank "
         f"aggregation)"),
        ("profiling", "--profile-ops (per-op replay, chain_overhead "
         "correction term), --trace "
         "(jax.profiler Perfetto, acg:* phase annotations)"),
        ("timeline tracing", f"--timeline FILE (cross-rank span "
         f"timeline as Chrome trace-event JSON, one pid per part, "
         f"barrier-timestamp clock alignment; "
         f"scripts/check_timeline.py validates, "
         f"scripts/trace_report.py summarises), --trace capture "
         f"analysis (measured per-op-class seconds, "
         f"overlap-efficiency score, straggler attribution; feeds the "
         f"--explain measured-vs-predicted comm verdict and replaces "
         f"--profile-ops replay estimates); 'tracing' section + "
         f"acg_trace_* metrics; schema {STATS_SCHEMA}"),
        ("communication-avoiding recurrences", "--algorithm sstep:S "
         "(ONE Gram allreduce per S iterations -- mesh reduction count "
         "2/iter -> 1/S-block; Chebyshev basis at S>=4) | pipelined:L "
         "(p(l)-CG: ONE fused allreduce/iter consumed L iterations "
         "later; restarted on sqrt breakdown); single-device, sharded "
         "gen-direct and dist tiers; builder classic/pipelined "
         "emission pinned byte-identical (acg_tpu.recurrence)"),
        ("persistent fused iteration", "--kernels fused on the mesh "
         "(--nparts): interior/border OVERLAPPED SpMV -- one-sided "
         "halo DMA (--comm dma) or all_to_all issued first, interior "
         "rows computed in flight, border rows finished after the "
         "recv wait; builder-emitted classic + pipelined, bitwise "
         "equal to the unsplit tier; comm ledger declares the overlap "
         "model the --explain verdict prices (exposed halo = max(0, "
         "halo - interior SpMV)); bench.py --overlap measures it"),
        ("matrix-free operators", "--operator stencil | "
         "stencil:poisson1d|2d|3d:N | stencil:aniso2d:N:EPS | "
         "user:NAME (acg_tpu.ops.operator): A as a jitted apply -- "
         "plane values GENERATED inside the SpMV, zero matrix HBM "
         "traffic, trajectories bitwise-equal to the assembled DIA "
         "tier on classic/sstep/jacobi/batched/dist (FMA-level on "
         "the apply-chaining pipelined/cheby/ABFT setups); rides classic/pipelined, sstep:S / pipelined:L, "
         "--nrhs (single device), --precond jacobi (analytic "
         "diagonal)/cheby:K, --abft (checksum c = A^T 1 through the "
         "apply), and the --nparts mesh (generated local planes "
         "behind the existing halo/ghost machinery; --kernels fused "
         "splits the stencil apply interior|border, --comm dma "
         "rides unchanged); register_operator hooks user-supplied "
         "jitted operators (diagonal_fn arms jacobi); in-kernel "
         "Pallas stencil path under --kernels pallas; operator "
         "identity rides the stats manifest + bench_diff case key; "
         "bench.py --matfree measures matrix-free vs assembled"),
        ("perf observability", f"--explain (compiled cost_analysis/"
         f"memory_analysis introspection, comm ledger, roofline "
         f"verdict); 'costmodel'/'memory' keys in the {STATS_SCHEMA} "
         f"stats twin"),
        ("communication observatory", "--commbench FILE (mesh "
         "collective microbenchmarks: psum/all_reduce latency, "
         "all_to_all + collective_permute sweeps, per-edge one-sided "
         "halo_dma put/wait timing by ring distance, fitted t = alpha "
         "+ beta*bytes per kind; measured SpMV/halo/reduction segment "
         "decomposition from the recurrence builder's own emission; "
         "acg-tpu-commbench/1 document with a content-hashed "
         "calibration id), --explain --calibration FILE (comm priced "
         "from the fitted alpha-beta, predicted-vs-measured with "
         "provenance; calibration ids ride the stats manifest, "
         "convergence-log meta line and bench_diff case keys), "
         "--no-probe-cache (bypass the backend-keyed on-disk triad-"
         "probe sidecar); acg_commbench_* metric families"),
        ("decision planner", f"--autotune (enumerate + price the "
         f"candidate program space -- recurrence x kernels x "
         f"transport x precond -- from the perfmodel HBM roofline, "
         f"the --calibration alpha-beta comm fits over each "
         f"recurrence's reduction schedule, and the Lanczos-kappa CG "
         f"bound; S/L/cheby degree chosen numerically; top-2 verified "
         f"by short timed probes, winner dispatched), --plan FILE / "
         f"--explain --plan (ranked acg-tpu-plan/1 document with "
         f"calibration + kappa provenance and typed refusal reasons, "
         f"no solve), plan-vs-actual self-correction through the "
         f"--history ledger, --serve --autotune (plan on operator-"
         f"cache miss, replan on calibration change); 'plan' section "
         f"in the {STATS_SCHEMA} twin, acg_plan_* metric families, "
         f"scripts/history_report.py --fail-on-misprediction PCT"),
        ("bench gating", "bench.py --baseline FILE --fail-on-regress "
         "PCT; scripts/bench_diff.py (diffs --stats-json or bench-row "
         "captures case-by-case, nonzero exit on regression)"),
        ("preconditioning", f"--precond none|jacobi|bjacobi[:BS]|"
         f"cheby:K (PCG / pipelined-PCG on every device tier + the "
         f"host oracle; 'none' lowers byte-identical programs), "
         f"--aniso EPS (stretched-grid ill-conditioned SPD generator "
         f"for gen:poisson2d), precond: fault site, 'precond' stats "
         f"section in the {STATS_SCHEMA} twin"),
        ("service metrics", f"--metrics-file (Prometheus textfile, "
         f"atomic rename, flushed on exit/SIGTERM), --metrics-port "
         f"(stdlib /metrics endpoint), --soak N + --fail-on-drift PCT "
         f"(EWMA latency-drift gate, exit 7; bench.py --soak too); "
         f"registry snapshot ('metrics') and soak report ('soak') "
         f"ride the {STATS_SCHEMA} stats twin"),
        ("numerical health", f"--audit-every K (in-loop true-residual "
         f"audit through each tier's own SpMV; relative gap in the "
         f"'health' stats section + acg_health_* metrics + a 'gap' "
         f"trace column), --gap-threshold G + --on-gap "
         f"warn|replace|abort (accuracy_degraded events; replace = "
         f"residual-replacement restart via the recovery driver), "
         f"--stall-window N (device-side stagnation/sign detectors); "
         f"Lanczos kappa estimate + predicted-vs-measured iterations "
         f"from the recorded (alpha, beta) in 'health' and the "
         f"--explain convergence verdict; soak tracks gap drift; "
         f"schema {STATS_SCHEMA}"),
        ("survivability", f"--ckpt FILE --ckpt-every K (solver-state "
         f"snapshots: full loop carry, atomic rename, checksummed "
         f"header; chunked solves iteration-identical to "
         f"uninterrupted; dist commits under one agreed sequence "
         f"number) + --resume FILE (continue to the ORIGINAL "
         f"tolerance; pre-crash + post-resume iterations match an "
         f"uninterrupted run), --abft [--abft-threshold T] "
         f"(Huang-Abraham checksum SpMV at the --audit-every cadence "
         f"-- detects silent bit-level SpMV corruption on device, "
         f"rides ONE fused reduction on the mesh tiers), rollback = "
         f"the recovery ladder's first rung (before restart/fallback/"
         f"abort), --heartbeat SECS (dead-peer detection during the "
         f"solve collective; relaunch with --resume), fault sites "
         f"sdc:flip@K (finite sign flip, invisible to non-finite "
         f"guards -- the ABFT test vector) and crash:exit@K "
         f"(hard os._exit between snapshot commits; refuses without "
         f"--ckpt); 'ckpt' stats section + acg_ckpt_*/acg_abft_* "
         f"metrics; schema {STATS_SCHEMA}"),
        ("live observatory", f"--status-port PORT / --status-file FILE "
         f"(in-flight acg-tpu-status/1 JSON: phase, iteration, "
         f"residual trail, iterations/sec, ETA from the Lanczos kappa "
         f"CG-bound falling back to the measured rate, per-part "
         f"imbalance, last events, soak progress; the port also "
         f"answers /metrics), --history DIR (date-partitioned "
         f"acg-tpu-history/1 run ledger; scripts/history_report.py "
         f"trends, bench_diff.py --baseline-from-history picks the "
         f"best USABLE capture and refuses an all-unavailable "
         f"ledger), --slo latency=S,iters=N,gap=G + --fail-on-slo "
         f"(acg_slo_target/acg_slo_breaches_total/acg_slo_burn_ratio "
         f"families, slo-breach events, exit 8); --progress "
         f"heartbeats carry the same it/s + ETA on every tier incl. "
         f"the host oracle; 'slo' stats section, schema "
         f"{STATS_SCHEMA}"),
        ("batched solves", f"--nrhs B (multi-RHS CG: one batched "
         f"program solves B systems against the shared matrix -- "
         f"multi-vector SpMV amortizes matrix HBM traffic B-fold, "
         f"per-RHS dots fuse into B-wide reductions with the mesh "
         f"collective count INVARIANT in B, converged columns freeze "
         f"via per-RHS masks; B=1/flag-absent runs byte-identical "
         f"single-RHS programs), --block-cg (true block-CG: shared "
         f"Krylov block, B x B Gram solves, rank deflation on "
         f"breakdown; fewer total iterations than B independent "
         f"solves on --aniso), per-RHS residual ring in "
         f"--convergence-log, per-RHS soak percentiles, status-doc "
         f"ETA keyed to the slowest unconverged RHS, batched "
         f"checkpoint carries (a batch survives preemption and "
         f"--resume-repartition); 'batch' stats section, schema "
         f"{STATS_SCHEMA}"),
        ("elastic recovery", "--supervise (survivor-mesh process "
         "supervisor: watches the exit-code contract, relaunches with "
         "--resume -- shrinking --nparts with --resume-repartition on "
         "a lost peer -- under --relaunch-budget/--relaunch-backoff; "
         "recovery: section, acg_recovery_* metrics, status-doc "
         "degraded key), --resume-repartition (restore an N-part "
         "snapshot onto an M-part mesh or the single-device/host "
         "tiers via the global row-permutation sidecar), --ckpt-secs "
         "S (wall-clock snapshot cadence), --chaos SEED[:N] (seeded "
         "fault campaign through the supervisor; per-schedule "
         "converged/agreed-abort/WRONG-ANSWER verdicts into the "
         "--history ledger, exit 96 on any wrong-answer-green)"),
        ("solver service", "--serve (long-lived daemon: POST /solve "
         "JSON requests against the owned mesh; GET /status /metrics "
         "/healthz, POST /shutdown; operator + compiled-program "
         "caches make repeated request shapes ZERO-ingest/ZERO-"
         "compile -- acg_serve_cache_* families), --serve-port/"
         "--serve-queue-depth/--serve-deadline (bounded queue + "
         "per-request deadlines; typed 429/503/504 sheds, "
         "acg_serve_shed_total), --slo burn drives the DEGRADE-"
         "BEFORE-REFUSE ladder (acg_serve_degraded_total), "
         "--serve-coalesce B (compatible queued requests merge into "
         "one batched multi-RHS solve, bitwise-equal to single "
         "service; acg_serve_coalesced_total), request isolation "
         "(typed error answers, poisoned cache invalidation, bounded "
         "retries -- the daemon never dies to a request), --serve "
         "--supervise (relaunch with WARM operator-cache restore "
         "from --ckpt serve state; --grow-after N regrows a shrunken "
         "mesh, acg_recovery_regrows_total), --serve --chaos SEED[:N] "
         "(seeded faults against the LIVE daemon, per-request answer "
         "verification, exit 96 on wrong-answer-green), "
         "--serve-faults (honour per-request fault fields -- chaos "
         "hook only); acg_serve_* metric families"),
        ("request observatory", "--serve request-scoped observability "
         "(acg_tpu.reqtrace): every request carries a request_id "
         "(client-supplied request_id/traceparent or generated), "
         "echoed in responses, structured events and chaos "
         "verification rows; --access-log FILE (append-only "
         "acg-tpu-access/1 JSONL -- one row per request with outcome, "
         "per-stage seconds and batch/cache/degrade/plan provenance; "
         "scripts/access_report.py p50/p95/p99 + tail decomposition, "
         "scripts/check_access_log.py validator), --serve --timeline "
         "FILE (the service timeline: worker batch row + one lane "
         "per in-flight request), GET /requests (last-K completed + "
         "in-flight request documents), status-doc requests: block, "
         "acg_serve_stage_seconds{stage} / acg_serve_inflight / "
         "acg_serve_queue_depth_high_water"),
    ]
    for k, v in rows:
        out.write(f"{k}: {v}\n")
    from acg_tpu.errors import exit_code_table
    out.write("exit codes:\n")
    for code, origin, meaning in exit_code_table():
        out.write(f"  {code:>3}  [{origin}] {meaning}\n")
    return 0


def _log(args, msg, t0=None):
    if args.verbose:
        if t0 is not None:
            sys.stderr.write(f"{msg} done in {time.perf_counter() - t0:.6f} seconds\n")
        else:
            sys.stderr.write(msg + "\n")


def _validate_numfmt(fmt: str) -> str:
    """Validate ``--numfmt`` through the fmtspec parser (the reference
    does the same via ``fmtspec_parse``, ``acg/fmtspec.c:224``) and
    normalise it for the output writers: exactly one floating-point
    conversion; integer conversions like ``%d`` are rejected --
    ``"%d" % 1.5`` is valid Python but silently truncates every solution
    value -- as are ``*`` width/precision (no argument to consume) and
    hexfloat ``%a`` (the array writers apply the spec with Python's
    ``%``, which lacks it).  C length modifiers (``%lg``) are accepted
    and stripped, matching printf's type-promotion semantics."""
    import dataclasses

    from acg_tpu import fmtspec

    try:
        spec = fmtspec.parse(fmt)
    except fmtspec.FmtSpecError as e:
        raise SystemExit(f"acg-tpu: invalid --numfmt {fmt!r}: {e}")
    if (not spec.is_float or spec.needs_star_args
            or spec.conversion in "aA"):
        raise SystemExit(
            f"acg-tpu: invalid --numfmt {fmt!r}: need a single "
            f"floating-point conversion (e.g. %.17g, %e, %12.6f)")
    return str(dataclasses.replace(spec, length=""))


def _parse_gen_spec(spec: str):
    """``gen:poisson2d:N | gen:poisson3d:N | gen:irregular:N[:AVGDEG]``
    -> (kind, dim, n, N, avg_degree)."""
    parts = spec.split(":")
    kind = parts[1] if len(parts) > 1 else ""
    try:
        if kind in ("poisson2d", "poisson3d"):
            if len(parts) != 3:
                raise ValueError
            dim = 2 if kind == "poisson2d" else 3
            n = int(parts[2])
            if n <= 0:
                raise ValueError
            return ("poisson", dim, n, n ** dim, None)
        if kind == "irregular":
            if len(parts) not in (3, 4):
                raise ValueError
            n = int(parts[2])
            avg = float(parts[3]) if len(parts) == 4 else 16.0
            if n <= 0 or avg <= 0:
                raise ValueError
            return ("irregular", 0, n, n, avg)
        raise ValueError
    except ValueError:
        raise SystemExit(
            f"acg-tpu: invalid generator spec {spec!r}: expected "
            f"gen:poisson2d:N | gen:poisson3d:N | gen:irregular:N[:AVGDEG]")


def synthesize_host_matrix(spec_str: str, aniso=None, seed: int = 42):
    """``gen:`` spec -> host :class:`~acg_tpu.matrix.SymCsrMatrix` --
    ONE dispatch shared by the solve pipeline and the chaos campaign's
    verification oracle (acg_tpu.supervisor), so the matrix verified
    against can never drift from the matrix solved."""
    from acg_tpu.io.generators import (aniso_poisson2d_coo,
                                       irregular_spd_coo, poisson2d_coo,
                                       poisson3d_coo)
    from acg_tpu.matrix import SymCsrMatrix

    kind, dim, n, N, avg = _parse_gen_spec(spec_str)
    if kind == "poisson" and aniso is not None:
        r, c, v, N = aniso_poisson2d_coo(n, aniso)
    elif kind == "poisson":
        r, c, v, N = (poisson2d_coo if dim == 2 else poisson3d_coo)(n)
    else:
        r, c, v, N = irregular_spd_coo(n, avg_degree=avg, seed=seed)
    return SymCsrMatrix.from_coo(N, r, c, v)


def _build_cli_operator(args, n: int, dtype):
    """Instantiate the armed ``--operator`` for this solve (device
    dtype resolved), validated against the matrix being solved; records
    the identity string for the stats manifest / bench case key."""
    from acg_tpu.ops.operator import build_operator

    gen = _parse_gen_spec(args.A) if args.A.startswith("gen:") else None
    try:
        op = build_operator(args._operator_spec, dtype, gen=gen,
                            aniso=args.aniso, nrows=n)
    except ValueError as e:
        raise SystemExit(f"acg-tpu: {e}")
    args._operator_id = op.identity()
    return op


def _gen_direct_min() -> int:
    """Row threshold above which gen:poisson specs skip host CSR
    assembly and build DIA planes on device (env-overridable so tests
    can exercise the direct path at tiny sizes)."""
    import os

    return int(os.environ.get("ACG_TPU_GEN_DIRECT_MIN", 2 ** 24))


def _solve_generated_direct(args, dim, n, N, jax, jnp, dtype,
                            vec_dtype=None) -> int:
    """The zero-transfer large-stencil path: DIA planes assembled on
    device (``poisson_dia_device``), solved by the compiled single-chip
    programs.  This is what makes the north-star 512^3 problem (134M
    rows) reachable from the CLI at all -- a Matrix Market file for it
    would be ~25 GB of text and the host COO/CSR route needs a
    multi-GB upload (BASELINE.md round-2 notes)."""
    import numpy as np

    from acg_tpu.errors import (AcgError, BreakdownError,
                                NotConvergedError)
    from acg_tpu.io.generators import poisson_dia_device
    from acg_tpu.io.mtxfile import vector_mtx, write_mtx
    from acg_tpu.ops.spmv import DiaMatrix
    from acg_tpu.solvers import StoppingCriteria
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    unsupported = [flag for flag, on in [
        (f"--solver {args.solver}",
         args.solver in ("host", "host-native", "petsc")),
        ("b/x0 input files", bool(args.b or args.x0)),
        ("--output-comm-matrix", args.output_comm_matrix),
        (f"--spmv-format {args.spmv_format}",
         args.spmv_format not in ("auto", "dia")),
        ("--nrhs/--block-cg (the batched tiers need the host-CSR "
         "ingest path; lower ACG_TPU_GEN_DIRECT_MIN only for "
         "single-RHS solves)", getattr(args, "_batched", False)),
    ] if on]
    if unsupported:
        raise SystemExit(
            f"acg-tpu: {args.A}: direct on-device assembly "
            f"(N={N:,} rows) does not support: {', '.join(unsupported)} "
            f"(these need a host-side matrix; use a file or a smaller "
            f"gen: spec)")

    vec_dtype = dtype if vec_dtype is None else vec_dtype

    # multi-part / multi-controller / manufactured / refined
    # configurations run the SHARDED assembly + solve
    # (parallel/sharded_dia): per-shard on-device planes, halo exchange
    # derived by the SPMD partitioner.  This makes the north-star
    # configuration -- gen:poisson3d:512 --multihost --nparts N
    # [--refine] -- expressible end-to-end with O(N/P) device memory per
    # chip and O(1) host memory per controller.
    if (args.nparts > 1 or args.multihost or args.coordinator is not None
            or args.manufactured_solution or args.refine):
        if getattr(args, "_operator_spec", None) is not None:
            raise SystemExit(
                "acg-tpu: --operator does not reach the sharded "
                "gen-direct tier (parallel/sharded_dia derives its "
                "halo from the SPMD partitioner over stored planes); "
                "use the host-ingest mesh path (raise "
                "ACG_TPU_GEN_DIRECT_MIN above N) or a single-chip "
                "solve")
        return _solve_generated_sharded(args, dim, n, N, jax, jnp, dtype,
                                        vec_dtype)

    t0 = time.perf_counter()
    if getattr(args, "_operator_spec", None) is not None:
        # matrix-free at gen-direct sizes: NOTHING is assembled, on
        # device or off -- the operator replaces even the on-device
        # plane build (--epsilon already refused at validation)
        A = _build_cli_operator(args, N, dtype)
    else:
        planes, offsets, _ = poisson_dia_device(n, dim, dtype=dtype)
        if args.epsilon:
            planes = list(planes)
            d = offsets.index(0)
            planes[d] = planes[d] + jnp.asarray(args.epsilon, dtype)
        A = DiaMatrix(data=tuple(planes), offsets=offsets,
                      nrows=N, ncols_padded=N)
    _log(args, "assemble DIA planes on device:", t0)
    args._phases.add("ingest", time.perf_counter() - t0)

    try:
        solver = JaxCGSolver(A, pipelined="pipelined" in args.solver,
                             precise_dots=args.precise_dots,
                             kernels=args.kernels, vector_dtype=vec_dtype,
                             replace_every=args.replace_every,
                             recovery=getattr(args, "_recovery", None),
                             trace=args._trace, progress=args.progress,
                             precond=getattr(args, "_precond", None),
                             health=getattr(args, "_health", None),
                             ckpt=getattr(args, "_ckpt", None),
                             algorithm=getattr(args, "_algorithm",
                                               None))
    except ValueError as e:
        raise SystemExit(f"acg-tpu: {e}")
    b = jnp.ones(N, dtype=vec_dtype)
    criteria = StoppingCriteria(
        maxits=args.max_iterations,
        residual_atol=args.residual_atol, residual_rtol=args.residual_rtol,
        diff_atol=args.diff_atol, diff_rtol=args.diff_rtol)
    t0 = time.perf_counter()
    from acg_tpu.tracing import profiler_trace
    with profiler_trace(args.trace):
        try:
            x = _run_solve(args, solver, b, criteria=criteria,
                           warmup=args.warmup,
                           host_result=bool(not args.quiet or args.output))
        except (NotConvergedError, BreakdownError) as e:
            sys.stderr.write(f"acg-tpu: {e}\n")
            _fold_phases(args, solver)
            solver.stats.fwrite(sys.stderr)
            _emit_telemetry(args, solver, matrix_id=args.A,
                            collective=False)
            return 1
        except AcgError as e:
            sys.stderr.write(f"acg-tpu: {e}\n")
            return 1
    _log(args, "solve:", t0)

    if args.profile_ops is not None:
        from acg_tpu.solvers.profile import profile_ops
        per_call = profile_ops(solver, b, reps=max(args.profile_ops, 1))
        _report_chain_overhead(per_call)
    # AFTER the replay tier: where the capture measured an op class,
    # the measured seconds supersede the replay estimate
    _attach_trace_analysis(args, solver)
    _fold_phases(args, solver)
    solver.stats.fwrite(sys.stderr)
    t_wb = time.perf_counter()
    _emit_solution(args, x)
    args._phases.add("writeback", time.perf_counter() - t_wb)
    _emit_telemetry(args, solver, matrix_id=args.A)
    return 0


def _report_chain_overhead(per_call: dict) -> None:
    """The --profile-ops replay's scalar-chain correction term, as a
    line next to the stats block it qualifies: chaining a scalar-result
    op (dot/nrm2/halo/allreduce) folds its scalar back into the carried
    vector to keep the data dependence, ~one axpy-equivalent extra per
    call -- those entries are upper bounds by about this much
    (solvers/profile.py docstring; the CLI prints it, library callers
    just read the "chain_overhead" key)."""
    co = per_call.get("chain_overhead")
    if co is not None:
        sys.stderr.write(
            f"per-op replay: chain_overhead {co:.3e} s/call -- "
            f"scalar-result chains (dot/nrm2/allreduce/halo) are upper "
            f"bounds by ~this\n")


def _run_solve(args, solver, b, *, x0=None, criteria=None, warmup=None,
               **solve_kwargs):
    """One CLI solve -- or, under ``--soak N``, the soak driver's N
    repeated solves (:mod:`acg_tpu.soak`).  ``warmup`` rides only the
    first soak solve (it absorbs the compile); every other kwarg rides
    them all.  The soak report lands on ``solver.stats.soak`` (the
    ``soak:`` stats section and its ``--stats-json`` twin) and on
    ``args._soak_report`` for the ``--fail-on-drift`` exit gate."""
    from acg_tpu import observatory

    # live-observatory tier: the status document's run header +
    # per-part imbalance.  Recorded HERE so every pipeline that
    # funnels through _run_solve (replicated read, gen-direct,
    # sharded-gen) gets the header; the distributed-read pipeline,
    # which dispatches its own solve, records its own
    prob = getattr(_inner_solver(solver), "problem", None)
    observatory.begin_solve(
        args.solver, criteria.maxits if criteria is not None else 0,
        rtol=args.residual_rtol, atol=args.residual_atol,
        matrix=args.A,
        nparts=int(getattr(prob, "nparts", 0) or args.nparts or 1))
    observatory.note_solver(solver)
    # the spectrum attach runs in a finally: a not-converged or
    # broken-down exit still gets its kappa estimate next to the
    # health: section -- that is exactly when it matters.  The SLO
    # verdict attaches there too (a breach on a failed solve is still
    # a breach)
    if not getattr(args, "soak", 0):
        if warmup is not None:
            solve_kwargs["warmup"] = warmup
        try:
            x = solver.solve(b, x0=x0, criteria=criteria,
                             **solve_kwargs)
        finally:
            _attach_health_spectrum(args, solver)
            _observe_slo(args, solver)
        return x
    from acg_tpu.soak import run_soak

    try:
        x, report = run_soak(
            solver, b, nsolves=args.soak, x0=x0, criteria=criteria,
            fail_on_drift=args.fail_on_drift,
            first_solve_kwargs=({"warmup": warmup} if warmup is not None
                                else None),
            solve_kwargs=solve_kwargs,
            progress_every=(max(1, args.soak // 10) if args.verbose
                            else 0))
    finally:
        _attach_health_spectrum(args, solver)
        # the soak driver already judged every solve; only the stats
        # section attach is left
        observatory.attach_slo(solver.stats)
    args._soak_report = report
    return x


def _observe_slo(args, solver) -> None:
    """Judge a completed single (non-soak) solve against the declared
    --slo objectives and attach the verdict to the stats block; the
    soak driver owns the per-solve judging on soak runs."""
    from acg_tpu import observatory
    if observatory.installed_slo() is None:
        return
    st = solver.stats
    lat = st.timings.get("solve", st.tsolve)
    observatory.slo_observe(
        st, latency=lat, iterations=int(st.niterations),
        gap=(st.health or {}).get("gap_last"))
    observatory.attach_slo(st)


def _attach_health_spectrum(args, solver) -> None:
    """Post-hoc spectrum estimation (the numerical-health tier): with
    an armed health spec AND a recorded trace, rebuild the Lanczos
    tridiagonal from the solve's (alpha, beta) window and attach the
    kappa / predicted-iterations report to the ``health:`` section.
    Free: the scalars were already recorded."""
    hs = getattr(args, "_health", None)
    if hs is None:
        return
    from acg_tpu import health as health_mod
    inner = _inner_solver(solver)
    trace = getattr(inner, "last_trace", None)
    if trace is None:
        return
    pc = getattr(args, "_precond", None)
    try:
        health_mod.attach_spectrum(
            inner.stats, trace, args.residual_rtol,
            precond=str(pc) if pc is not None else None)
    except Exception as e:  # noqa: BLE001 -- health reporting must
        # never sink a solve that succeeded
        sys.stderr.write(f"acg-tpu: spectrum estimation failed "
                         f"({type(e).__name__}: {e})\n")


def _stage_sync(args, stage: str, code: int = 0) -> int:
    """Cross-controller STAGE SYNC: error agreement at a pipeline stage
    boundary (the acgerrmpi analog, parallel/erragree) -- every
    controller learns the worst status code so all exit together, and a
    dead peer trips the watchdog instead of wedging the pod in the next
    collective.  Pure status agreement: nothing is stored.  NOT the
    solver-state snapshots of ``--ckpt`` (acg_tpu.checkpoint), which
    serialise the loop carry to disk -- the two were both historically
    called "checkpoints"; this one is the barrier."""
    if not (args.multihost or args.coordinator is not None):
        return int(code)
    from acg_tpu.parallel.erragree import agree_status
    return agree_status(code, what=stage, timeout=args.err_timeout)


def _inner_solver(solver):
    """Unwrap --refine's RefinedSolver down to the device solver that
    carries the telemetry (trace, timings, problem layout)."""
    while hasattr(solver, "inner"):
        solver = solver.inner
    return solver


def _fold_phases(args, solver) -> None:
    """Fold the CLI's phase timer plus the inner solver's self-recorded
    phases (transfer/compile/solve) into the stats that are about to be
    printed -- idempotent (the timer consumes on merge), so error paths
    and the post-writeback stats-json both call it safely."""
    timer = getattr(args, "_phases", None)
    if timer is None:
        return
    st = solver.stats
    inner = _inner_solver(solver)
    if inner is not solver:
        # --refine: the wrapper's stats block is the one printed; adopt
        # the device solver's phases and trace
        for k, v in inner.stats.timings.items():
            st.timings[k] = st.timings.get(k, 0.0) + v
        inner.stats.timings.clear()
        if st.trace is None and inner.stats.trace is not None:
            st.trace = inner.stats.trace
    timer.merge_into(st.timings)


def _attach_trace_analysis(args, solver) -> None:
    """After the profiler stopped: parse the ``--trace`` capture into
    the ``tracing:`` stats section (measured per-op-class seconds,
    overlap efficiency, straggler attribution), replacing the
    --profile-ops replay estimates where the capture resolved an op
    class.  Analysis failures degrade to a self-describing section --
    a solve that succeeded must never die for its observability."""
    if not args.trace or solver is None:
        return
    from acg_tpu import tracing

    an = tracing.analyze_trace(args.trace)
    # the PRINTED stats (under --refine: the wrapper's block) carry the
    # section, same target _emit_telemetry writes to --stats-json
    tracing.attach(solver.stats, an)
    if not an.get("available"):
        sys.stderr.write(f"acg-tpu: --trace: capture analysis "
                         f"unavailable ({an.get('why', '?')})\n")


def _timeline_parts(solver, nparts: int) -> list[int]:
    """The part ids this controller's spans describe: the distributed
    problem's owned parts where one exists, else every part (single
    controller -- the SPMD program runs them in lockstep)."""
    inner = _inner_solver(solver)
    prob = getattr(inner, "problem", None)
    owned = getattr(prob, "owned_parts", None) if prob is not None else None
    if owned is not None:
        return [int(p) for p in owned]
    n = max(int(nparts), 1)
    import jax

    if jax.process_count() > 1:
        # sharded/multihost tiers without an explicit owned_parts list
        # shard parts contiguously across controllers (the mesh builds
        # process-major); the even split mirrors that layout
        pc, pi = jax.process_count(), jax.process_index()
        per = max(n // pc, 1)
        lo = min(pi * per, n)
        hi = n if pi == pc - 1 else min(lo + per, n)
        return list(range(lo, hi))
    return list(range(n))


def _emit_timeline(args, solver, nparts=1, collective=True) -> None:
    """Gather every controller's spans (clock-aligned) and write the
    Chrome trace-event timeline -- primary writes, everyone gathers
    (the _emit_telemetry collectivity contract)."""
    if not getattr(args, "timeline", None) \
            or getattr(args, "_timeline_written", False):
        return
    from acg_tpu import tracing
    from acg_tpu.parallel.multihost import is_primary

    payloads, clock = tracing.gather_timeline(
        parts=_timeline_parts(solver, nparts),
        timeout=args.err_timeout, collective=collective)
    # the once-only flag is set on EVERY rank right after the gather:
    # were it primary-only, a second _emit_telemetry call would skip
    # the collective on the primary while the peers enter the barrier
    # -- a mismatched collective
    args._timeline_written = True
    if not is_primary():
        return
    try:
        summary = tracing.export_chrome_trace(
            args.timeline, payloads, nparts=max(int(nparts), 1),
            clock=clock)
    except OSError as e:
        sys.stderr.write(f"acg-tpu: --timeline {args.timeline}: {e}\n")
        return
    tracing.attach(solver.stats, None, timeline=summary)
    sys.stderr.write(f"acg-tpu: timeline: {summary['nspans']} spans "
                     f"over {summary['nparts']} part(s) from "
                     f"{summary['nranks']} rank(s) -> "
                     f"{args.timeline}\n")


def _run_autotune(args, csr, part, nparts, b, dtype, vec_dtype) -> None:
    """Plan -> probe -> dispatch (--autotune): build the ranked plan,
    verify the top candidates by short timed probes, and mutate the
    parsed flags so the normal construction flow below dispatches the
    winner.  Probes failing is never fatal -- the flag-selected
    program dispatches with decision provenance ``fallback``."""
    from acg_tpu import planner

    err = sys.stderr
    doc = planner.plan_for_args(args, csr, nparts, dtype, vec_dtype)
    err.write(planner.render_plan(doc))
    if args.plan not in (None, "-"):
        try:
            planner.write_plan(doc, args.plan)
        except OSError as e:
            err.write(f"acg-tpu: --plan {args.plan}: {e}\n")
    decision = {"plan_id": doc["plan_id"],
                "calibration": doc["calibration"],
                "uncalibrated": bool(doc.get("uncalibrated")),
                "kappa_source": doc["kappa_source"],
                "correction_scale": doc["correction"]["scale"],
                "correction_nsamples": doc["correction"]["nsamples"],
                "key": doc["correction"]["key"]}
    probe_b = b[:, 0] if getattr(b, "ndim", 1) == 2 else b
    winner = planner.autotune_select(args, doc, csr, part, nparts,
                                     probe_b, dtype, vec_dtype, err)
    if winner is None:
        err.write("acg-tpu: autotune: every probe failed; dispatching "
                  "the flag-selected program (provenance: fallback)\n")
        args._plan_decision = {**decision, "source": "fallback"}
        return
    planner.apply_candidate_to_args(args, winner)
    err.write(f"acg-tpu: autotune: dispatching {winner['label']} "
              f"(predicted {winner['predicted_s_per_solve']:.3e} "
              f"s/solve, {winner['predicted_iterations']} its)\n")
    args._plan_decision = {
        **decision, "source": "planned", "selected": winner["label"],
        "algorithm": winner["algorithm"], "kernels": winner["kernels"],
        "comm": winner["comm"], "precond": winner["precond"],
        "predicted_s_per_solve": winner["predicted_s_per_solve"],
        "predicted_iterations": winner["predicted_iterations"],
    }


def _finalize_plan(args, solver) -> None:
    """Close one planned solve's feedback loop: the plan-vs-actual row
    (predicted vs measured s/solve + iterations, misprediction ratio)
    lands in the 'plan:' stats section -- and from there rides fwrite,
    --stats-json and the --history ledger, where the next planned run
    for the same (matrix, mesh, calibration) key consults it to
    rescale the model's constants."""
    dec = getattr(args, "_plan_decision", None)
    if dec is None or solver is None:
        return
    from acg_tpu import metrics
    st = solver.stats
    plan = dict(dec)
    measured = float(st.tsolve or 0.0)
    plan["measured_s_per_solve"] = measured
    plan["measured_iterations"] = int(st.niterations)
    pred = dec.get("predicted_s_per_solve")
    if pred and measured > 0:
        plan["misprediction_ratio"] = float(pred) / measured
        metrics.record_plan_misprediction(plan["misprediction_ratio"])
    st.plan = plan
    metrics.record_plan_decision(dec.get("source", "planned"))
    args._plan_decision = None  # one solve, one row


def _emit_telemetry(args, solver, *, matrix_id, nparts=1,
                    comm=None, collective=True) -> None:
    """The telemetry sinks: --convergence-log JSONL, the cross-rank
    aggregation, and the --stats-json document.  The rank gather is a
    COLLECTIVE (every controller calls it; argv -- and so the gating
    flags -- are identical across controllers), the file writes are
    primary-only.  Error paths pass ``collective=False``: a possibly
    one-sided failure must not enter a gather its peers may never
    reach (the erragree mismatched-collective rationale)."""
    if not (args.convergence_log or args.stats_json
            or getattr(args, "timeline", None)
            or getattr(args, "history", None)):
        return
    from acg_tpu import telemetry
    from acg_tpu.commbench import UNCALIBRATED
    from acg_tpu.parallel.multihost import is_primary

    # the active commbench calibration id, stamped on BOTH provenance
    # surfaces below (convergence-log meta line + stats manifest) from
    # one lookup so they can never drift
    _cal = getattr(args, "_calibration", None)
    cal_id = _cal["calibration_id"] if _cal is not None else UNCALIBRATED

    _fold_phases(args, solver)
    # the span timeline rides the same call points (success AND error
    # paths) so its gather keeps the collectivity contract below
    _emit_timeline(args, solver, nparts=nparts, collective=collective)
    inner = _inner_solver(solver)
    st = solver.stats
    trace = st.trace if st.trace is not None else inner.stats.trace
    if args.convergence_log and is_primary():
        try:
            if trace is not None:
                # calibration provenance on the meta line (the
                # stats-manifest twin below records the same id): a
                # log produced under a commbench calibration names it,
                # every other log says "uncalibrated"
                trace.meta_extra["calibration"] = cal_id
                trace.write_jsonl(args.convergence_log)
            else:
                sys.stderr.write(
                    f"acg-tpu: --convergence-log: no convergence trace "
                    f"was recorded (--solver {args.solver} has no "
                    f"in-loop telemetry hooks)\n")
        except OSError as e:
            sys.stderr.write(f"acg-tpu: {args.convergence_log}: {e}\n")
    if not (args.stats_json or getattr(args, "history", None)):
        return
    ranks = None
    payloads = None
    try:
        payload = telemetry.rank_payload(inner)
    except Exception as e:  # noqa: BLE001 -- telemetry must never sink
        # a solve that succeeded.  A STUB payload keeps the collective
        # below symmetric: skipping the gather on this rank alone would
        # leave the peers blocked on this rank's missing key (and
        # desynchronise the blob-gather generation counter)
        sys.stderr.write(f"acg-tpu: rank stats payload failed "
                         f"({type(e).__name__})\n")
        import jax
        payload = {"process": int(jax.process_index()),
                   "error": type(e).__name__}
    if collective:
        # gather_rank_stats owns the gather's failure handling
        # (reports + returns None)
        payloads = telemetry.gather_rank_stats(
            payload, timeout=args.err_timeout)
    else:
        import jax
        if jax.process_count() == 1:
            payloads = [payload]
    if payloads is not None:
        agg = telemetry.aggregate_ranks(payloads)
        ranks = {"per_rank": payloads, "aggregate": agg}
        if is_primary() and len(payloads) > 1:
            sys.stderr.write("acg-tpu: "
                             + telemetry.format_rank_report(agg) + "\n")
    if not is_primary():
        return
    extra = {"matrix": str(matrix_id), "solver": args.solver,
             "comm": comm, "nparts": int(nparts), "dtype": args.dtype,
             # the active commbench calibration id; joins the bench-diff
             # CASE KEY (perfmodel._calibration_keyed) so differently-
             # calibrated captures never diff silently
             "calibration": cal_id,
             "argv": list(sys.argv[1:])}
    pc = getattr(args, "_precond", None)
    if pc is not None:
        # the precond selection joins the CASE KEY downstream
        # (perfmodel._doc_case): preconditioned and plain captures must
        # never silently diff against each other
        extra["precond"] = str(pc)
    if getattr(args, "_batched", False):
        # nrhs/block join the case key too (perfmodel._batch_keyed):
        # a B-wide capture must never silently diff against a
        # single-RHS one
        extra["nrhs"] = int(args.nrhs)
        if args.block_cg:
            extra["block_cg"] = True
    if getattr(args, "_operator_id", None):
        # the operator identity joins the case key
        # (perfmodel._operator_keyed): a matrix-free capture must never
        # silently diff against an assembled one of the same system
        extra["operator"] = args._operator_id
    if args.aniso is not None:
        extra["aniso"] = float(args.aniso)
    kern = getattr(inner, "kernels", None)
    extra["kernels"] = kern if isinstance(kern, str) else args.kernels
    mesh = getattr(inner, "mesh", None)
    if mesh is not None:
        try:
            extra["mesh"] = {str(k): int(v)
                             for k, v in dict(mesh.shape).items()}
        except Exception:  # noqa: BLE001
            pass
    prob = getattr(inner, "problem", None)
    if prob is not None:
        extra["partition"] = {
            "nparts": int(prob.nparts),
            "nmax_owned": int(prob.nmax_owned),
            "local_format": prob.local.format,
            "nnz_total": int(prob.nnz_total),
            "halo_send_total": int(getattr(prob, "halo_send_total", 0)
                                   or 0),
            "nmax_ghost": int(prob.halo.nmax_ghost)
            if hasattr(prob.halo, "nmax_ghost") else None,
        }
    doc = None
    if args.stats_json:
        try:
            doc = telemetry.write_stats_json(
                args.stats_json, st,
                manifest=telemetry.run_manifest(**extra), ranks=ranks)
        except OSError as e:
            sys.stderr.write(f"acg-tpu: {args.stats_json}: {e}\n")
    # run-history ledger (acg_tpu.observatory, --history DIR): the same
    # document JSONL-appends to the date-partitioned ledger under one
    # index line -- error paths append too (a failed run is history
    # evidence), guarded once-only like the timeline
    if getattr(args, "history", None) \
            and not getattr(args, "_history_written", False):
        args._history_written = True
        from acg_tpu import observatory
        if doc is None:
            doc = telemetry.stats_document(
                st, manifest=telemetry.run_manifest(**extra),
                ranks=ranks)
        try:
            path = observatory.history_append(args.history, doc)
            sys.stderr.write(f"acg-tpu: history: appended to {path}\n")
        except OSError as e:
            sys.stderr.write(f"acg-tpu: --history {args.history}: "
                             f"{e}\n")


def _solve_distributed_read(args, jax, jnp, dtype, vec_dtype) -> int:
    """The --distributed-read pipeline: range-read ingest, local
    subdomain construction, distributed solve.  Kept separate from the
    replicated-read pipeline because its stages are per-controller-local
    by design (no full matrix exists anywhere to share code with)."""
    import os

    from acg_tpu.errors import (AcgError, BreakdownError,
                                NotConvergedError)
    from acg_tpu.io.mtxfile import read_mtx, vector_mtx, write_mtx
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.parallel.multihost import is_primary
    from acg_tpu.solvers import StoppingCriteria

    unsupported = [flag for flag, on in [
        ("a gen: spec (use the sharded direct path)",
         args.A.startswith("gen:")),
        ("text input (needs --binary; see mtx2bin --expand)",
         not args.binary),
        (f"--solver {args.solver}",
         args.solver in ("host", "host-native", "petsc")),
        ("b/x0 files with --manufactured-solution",
         args.manufactured_solution and bool(args.b or args.x0)),
        ("--profile-ops", args.profile_ops is not None),
        ("--kernels fused (needs the full-information build; the "
         "local-read flow holds other controllers' coupled-row lists "
         "as stubs)", args.kernels == "fused"),
        ("--diff-* criteria with --replace-every or --refine",
         (args.replace_every > 0 or args.refine)
         and (args.diff_atol > 0 or args.diff_rtol > 0)),
        ("--comm dma", args.comm in ("dma", "nvshmem")),
    ] if on]
    if unsupported:
        raise SystemExit(
            f"acg-tpu: --distributed-read does not support: "
            f"{', '.join(unsupported)}")

    # partition bounds: arbitrary (METIS/graph) partitions arrive here
    # PRE-APPLIED by ``mtx2bin --expand --partition`` (the matrix is
    # permuted so parts are contiguous) as a tiny bounds sidecar --
    # O(nparts) to read, keeping per-controller ingest O(local nnz).
    # --partition FILE names the sidecar explicitly; otherwise the
    # mtx2bin-written default next to the matrix is picked up.
    bounds = None
    bounds_path = args.partition
    if bounds_path is None and os.path.exists(args.A + ".bounds.mtx"):
        bounds_path = args.A + ".bounds.mtx"
    if bounds_path is not None:
        # the bounds sidecar is TEXT by construction (mtx2bin writes it
        # so); --partition-binary describes the original partition
        # VECTOR, not this sidecar -- reusing it here turned a valid
        # run into a parse failure (round-4 advisor finding).  Sniff
        # binary as a fallback for hand-made sidecars.
        try:
            # ValueError: the numpy-fallback text parser raises it (not
            # AcgError) when the data section is actually binary
            bmtx = read_mtx(bounds_path, binary=False)
        except (AcgError, ValueError):
            try:
                bmtx = read_mtx(bounds_path, binary=True)
            except AcgError as e:
                raise SystemExit(f"acg-tpu: {bounds_path}: {e}")
        bounds = np.asarray(bmtx.vals).reshape(-1).astype(np.int64)
        try:
            from acg_tpu.io.mtxfile import read_mtx_sizes
            n_check = read_mtx_sizes(args.A)[0]
        except (AcgError, OSError):
            n_check = None  # the matrix read below reports its own error
        if (bounds.size < 2 or bounds[0] != 0 or (np.diff(bounds) < 0).any()
                or (n_check is not None and bounds[-1] != n_check)):
            raise SystemExit(
                f"acg-tpu: {bounds_path} is not a part-bounds sidecar "
                f"(nparts+1 ascending boundaries from 0 to nrows).  For "
                f"--distributed-read, apply the partition VECTOR offline "
                f"with: mtx2bin IN OUT --expand --partition VECFILE, "
                f"then pass OUT here (its .bounds.mtx is found "
                f"automatically)")
        if args.nparts and args.nparts != bounds.size - 1:
            raise SystemExit(
                f"acg-tpu: --nparts {args.nparts} != {bounds.size - 1} "
                f"parts in {bounds_path}")

    nparts = (bounds.size - 1 if bounds is not None
              else args.nparts or len(jax.devices()))
    # two-phase ingest: the host-local reads (phase 1) are the stage
    # where one controller can fail alone, and they are stage-synced
    # BEFORE the uniform-shape allgather of phase 2 -- a failed peer
    # must never leave the others blocked in a mismatched collective
    ingest_rc = 0
    state = None
    try:
        t0 = time.perf_counter()
        state = DistributedProblem.read_local_subdomains(args.A, nparts,
                                                         bounds=bounds)
        _log(args, f"range-read + local build ({len(state[3])} of "
                   f"{nparts} parts on this controller):", t0)
    except (AcgError, OSError, SystemExit) as e:
        sys.stderr.write(f"acg-tpu: {e}\n")
        ingest_rc = 1
    rc = _stage_sync(args, "ingest", ingest_rc)
    if rc:
        if not ingest_rc:
            sys.stderr.write("acg-tpu: aborting: a peer controller failed "
                             "during ingest\n")
        return rc
    subs, bounds, n_rows, owned = state
    t_part = time.perf_counter()
    prob = DistributedProblem.assemble_local(
        subs, bounds, n_rows, nparts, owned, dtype=dtype,
        vector_dtype=vec_dtype)
    args._phases.add("ingest", t_part - t0)
    args._phases.add("partition", time.perf_counter() - t_part)

    comm_mtx_out = None
    if args.output_comm_matrix:
        # owned rows of the volume matrix are exact from local halo
        # plans; the P x P allgather-sum fills the rest (tiny)
        from acg_tpu.graph import comm_matrix as _cm
        M = _cm([prob.subs[p] for p in prob.owned_parts], nparts)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            M = np.sum(multihost_utils.process_allgather(M, tiled=False),
                       axis=0).astype(np.int64)
        comm_mtx_out = M

    n = prob.n
    rng = np.random.default_rng(args.seed)
    xsol = None
    if args.manufactured_solution:
        # identical seed -> identical xsol on every controller; b = A xsol
        # assembled from the LOCAL blocks only (the distributed host
        # SpMV, computed per-part: b_p = A_local x_owned + A_ghost x_ghost)
        xsol = rng.standard_normal(n)
        xsol /= np.linalg.norm(xsol)
        b = np.zeros(n)
        _owned_spmv_windows(prob, xsol, b)
        # b needs only the owned slices: scatter() reads owned parts only
    elif args.b:
        b = None
    else:
        b = np.ones(n)
    x0 = None
    if args.b or args.x0:
        # per-controller WINDOW reads of binary array vectors (the
        # input mirror of the distributed write): I/O stays O(local
        # rows).  Host-local reads can fail one-sided, so agree at a
        # stage-sync BEFORE entering the solve collective (the ingest
        # sync rationale).
        rhs_rc = 0
        perm_path = (args.A + ".perm.mtx"
                     if os.path.exists(args.A + ".perm.mtx") else None)
        try:
            if args.b:
                b = _read_vector_windows(args.b, prob, perm_path)
            if args.x0:
                x0 = _read_vector_windows(args.x0, prob, perm_path)
        except (AcgError, OSError) as e:
            sys.stderr.write(f"acg-tpu: {e}\n")
            rhs_rc = 1
        rc = _stage_sync(args, "rhs", rhs_rc)
        if rc:
            if not rhs_rc:
                sys.stderr.write("acg-tpu: aborting: a peer controller "
                                 "failed reading b/x0\n")
            return rc

    criteria = StoppingCriteria(
        maxits=args.max_iterations,
        residual_atol=args.residual_atol, residual_rtol=args.residual_rtol,
        diff_atol=args.diff_atol, diff_rtol=args.diff_rtol)
    try:
        solver = DistCGSolver(prob, pipelined="pipelined" in args.solver,
                              precise_dots=args.precise_dots,
                              kernels=args.kernels,
                              replace_every=args.replace_every,
                              recovery=getattr(args, "_recovery", None),
                              trace=args._trace, progress=args.progress,
                              precond=getattr(args, "_precond", None),
                              health=getattr(args, "_health", None),
                              ckpt=getattr(args, "_ckpt", None),
                              algorithm=getattr(args, "_algorithm",
                                                None))
    except ValueError as e:
        sys.stderr.write(f"acg-tpu: {e}\n")
        _stage_sync(args, "solve", 1)
        return 1
    if args.refine:
        # f64 outer residuals from THIS controller's host blocks only
        # (no full matrix anywhere); inner --dtype solves on the mesh.
        # The outer iteration needs a GLOBALLY consistent b (and x0):
        # windowed per-controller vectors are combined, else each
        # controller's residual norms -- and therefore the refinement
        # control flow -- would diverge across the pod.
        from acg_tpu.solvers.refine import RefinedSolver
        if args.manufactured_solution or args.b:
            b = _allgather_sum(b, prob)
        if x0 is not None:
            x0 = _allgather_sum(x0, prob)
        solver = RefinedSolver(solver, _dist_host_matvec(prob), n=n,
                               nnz=prob.nnz_total,
                               inner_rtol=args.refine_rtol,
                               inner_maxits=args.refine_inner_maxits)
    # live-observatory run header (this pipeline dispatches its own
    # solve rather than funnelling through _run_solve)
    from acg_tpu import observatory
    observatory.begin_solve(args.solver, criteria.maxits,
                            rtol=args.residual_rtol,
                            atol=args.residual_atol, matrix=args.A,
                            nparts=int(prob.nparts))
    observatory.note_solver(solver)
    t0 = time.perf_counter()
    from acg_tpu.tracing import profiler_trace
    with profiler_trace(args.trace):
        try:
            if args.refine:
                # refined solutions come back as host f64 (the outer
                # iteration lives there); the distributed write then
                # range-writes host windows instead of device shards
                x = solver.solve(b, x0=x0, criteria=criteria,
                                 warmup=args.warmup)
            else:
                x = solver.solve(b, x0=x0, criteria=criteria,
                                 warmup=args.warmup,
                                 host_result=not args.output)
        except (NotConvergedError, BreakdownError) as e:
            # the stats block carries the resilience event log -- most
            # needed exactly when recovery failed
            sys.stderr.write(f"acg-tpu: {e}\n")
            _fold_phases(args, solver)
            if is_primary():
                solver.stats.fwrite(sys.stderr)
            _emit_telemetry(args, solver, matrix_id=args.A,
                            nparts=nparts, collective=False)
            _stage_sync(args, "solve", 1)
            return 1
        except AcgError as e:
            # solve-time configuration refusals (e.g. replace_every + an
            # armed fault injector) carry typed AcgErrors
            sys.stderr.write(f"acg-tpu: {e}\n")
            _stage_sync(args, "solve", 1)
            return 1
    _attach_trace_analysis(args, solver)
    _log(args, "solve:", t0)
    rc = _stage_sync(args, "solve", 0)
    if rc:
        sys.stderr.write("acg-tpu: aborting: a peer controller failed "
                         "during the solve\n")
        return rc

    if comm_mtx_out is not None and is_primary():
        _write_comm_matrix(comm_mtx_out, nparts)

    if args.output:
        rc = _distributed_write(args, solver, x, xsol, n)
        if rc == 0:
            _emit_telemetry(args, solver, matrix_id=args.A,
                            nparts=nparts)
        return rc

    _fold_phases(args, solver)
    if not is_primary():
        _emit_telemetry(args, solver, matrix_id=args.A, nparts=nparts)
        return 0
    solver.stats.fwrite(sys.stderr)
    if xsol is not None:
        err0 = np.linalg.norm(xsol)
        err = np.linalg.norm(x - xsol)
        sys.stderr.write(f"initial error 2-norm: {err0:.15g}\n")
        sys.stderr.write(f"error 2-norm: {err:.15g}\n")
    # a partition-permuted matrix (mtx2bin --partition) solves in
    # permuted row order; the emitter maps the solution back to the
    # input ordering via the perm sidecar
    t_wb = time.perf_counter()
    _emit_solution(args, x, _load_perm_sidecar(args.A, n))
    args._phases.add("writeback", time.perf_counter() - t_wb)
    _emit_telemetry(args, solver, matrix_id=args.A, nparts=nparts)
    return 0


def _write_comm_matrix(M: np.ndarray, nparts: int) -> None:
    """Part-to-part communication volumes to stdout as Matrix Market
    (``--output-comm-matrix``, ``cuda/acg-cuda.c:1712-1780``) -- shared
    by the replicated and distributed-read paths so their formats
    cannot diverge."""
    from acg_tpu.io.mtxfile import MtxFile, write_mtx

    nz = np.nonzero(M)
    write_mtx(sys.stdout.buffer, MtxFile(
        object="matrix", format="coordinate", field="integer",
        symmetry="general", nrows=nparts, ncols=nparts,
        nnz=len(nz[0]), rowidx=nz[0], colidx=nz[1],
        vals=M[nz]), numfmt="%d")


def _owned_spmv_windows(prob, x: np.ndarray, out: np.ndarray) -> None:
    """``out[lo:hi] = (A @ x)[lo:hi]`` for every part this controller
    owns, from its host blocks (f64 scipy): the per-part distributed
    host SpMV shared by the manufactured-b assembly and the refinement
    matvec (the ``acgsymcsrmatrix_dsymvmpi`` role,
    ``cuda/acg-cuda.c:2115``)."""
    for p in prob.owned_parts:
        s = prob.subs[p]
        lo, hi = prob.band_bounds[p], prob.band_bounds[p + 1]
        yp = s.A_local @ x[lo:hi]
        if s.nghost:
            yp = yp + s.A_ghost @ x[s.global_ids[s.nowned:]]
        out[lo:hi] = yp


def _allgather_sum(y: np.ndarray, prob=None) -> np.ndarray:
    """Combine per-controller owned-window vectors (zeros elsewhere)
    into the global vector across processes.

    With ``prob``, only each process's owned SPAN (bounding box of its
    windows, padded to the mesh max) is exchanged -- O(N) total for
    balanced contiguous assignments, instead of the O(P*N) a full
    per-process allgather would cost (at 512^3 x 16 controllers that
    difference is ~17 GB of host temporaries per call).  Rows outside a
    process's windows are zero on that process, so overlapping spans
    still sum correctly."""
    import jax

    if jax.process_count() == 1:
        return y
    from jax.experimental import multihost_utils

    y = np.asarray(y)
    if prob is None:
        return np.sum(multihost_utils.process_allgather(y, tiled=False),
                      axis=0)
    lo = min(int(prob.band_bounds[p]) for p in prob.owned_parts)
    hi = max(int(prob.band_bounds[p + 1]) for p in prob.owned_parts)
    meta = multihost_utils.process_allgather(
        np.asarray([lo, hi], np.int64), tiled=False)
    span = int((meta[:, 1] - meta[:, 0]).max())
    buf = np.zeros(span)
    buf[: hi - lo] = y[lo:hi]
    data = multihost_utils.process_allgather(buf, tiled=False)
    out = np.zeros_like(y)
    for (plo, phi), row in zip(meta, data):
        out[plo:phi] += row[: phi - plo]
    return out


def _dist_host_matvec(prob):
    """``matvec(x) -> A @ x`` in f64 from THIS controller's host blocks
    only: per-part windows (:func:`_owned_spmv_windows`) combined by a
    span-wise cross-process sum -- O(N) vector traffic, the MATRIX
    never leaves its controller."""
    def mv(x):
        y = np.zeros(prob.n)
        _owned_spmv_windows(prob, x, y)
        # each row is owned by exactly one part/process; unowned rows
        # are zero, so the element-wise sum assembles A @ x
        return _allgather_sum(y, prob)

    return mv


def _read_vector_windows(path, prob, perm_path=None) -> np.ndarray:
    """Assemble a global-length vector by reading ONLY this controller's
    owned part windows from a binary array vector file
    (:func:`acg_tpu.io.mtxfile.read_vector_window`) -- unowned entries
    stay zero and are never read by the stacked scatter.

    For a partition-PERMUTED matrix (``mtx2bin --partition``;
    ``perm_path`` = its sidecar) the user's vector file is in the
    ORIGINAL row ordering, so each owned permuted window [lo, hi) maps
    through the perm sidecar -- itself window-read, O(local rows) -- to
    scattered original rows, gathered with coalesced range reads
    (:func:`acg_tpu.io.mtxfile.read_vector_rows`).  The full perm and
    the full vector are never materialised on any controller (round-4
    verdict item 6; ref ``mtxfile.h:997-1087``)."""
    from acg_tpu.io.mtxfile import (read_vector_rows, read_vector_window)

    v = np.zeros(prob.n)
    for p in prob.owned_parts:
        lo, hi = int(prob.band_bounds[p]), int(prob.band_bounds[p + 1])
        if perm_path is None:
            v[lo:hi] = read_vector_window(path, lo, hi,
                                          expect_nrows=prob.n)
        else:
            from acg_tpu.errors import AcgError, ErrorCode
            try:
                orig = read_vector_window(perm_path, lo, hi,
                                          expect_nrows=prob.n)
            except AcgError as e:
                # name the sidecar's required convention directly: a
                # hand-made or text perm file fails deep in the window
                # reader with a message about the VECTOR file otherwise
                raise AcgError(
                    e.code,
                    f"{perm_path}: not a readable perm sidecar -- "
                    f"mtx2bin --partition writes it as a BINARY integer "
                    f"array of 1-based original row numbers, one per "
                    f"permuted row ({e})")
            orig = orig.astype(np.int64) - 1  # sidecar rows are 1-based
            if orig.size and (orig.min() < 0 or orig.max() >= prob.n):
                oob = int(orig.min() + 1) if orig.min() < 0 \
                    else int(orig.max() + 1)
                raise AcgError(
                    ErrorCode.INVALID_VALUE,
                    f"{perm_path}: sidecar entry {oob} outside the "
                    f"1-based row range [1, {prob.n}] -- stale or "
                    f"hand-made sidecar?  (mtx2bin --partition writes "
                    f"1-based original row numbers)")
            v[lo:hi] = read_vector_rows(path, orig, expect_nrows=prob.n)
    return v


def _distributed_write(args, solver, x_st, xsol, n: int) -> int:
    """Rootless distributed solution output (the reference's
    ``mtxfile_fwrite_mpi_double`` role, ``mtxfile.h:1087``): each
    controller extracts its owned part windows from ITS OWN device
    shards of the stacked solution and range-writes them into the
    shared output file; the primary writes only the header.  No
    full-vector gather happens on any controller -- at 512^3 that
    avoids a 0.5-1 GB host gather per output (round-3 verdict item 5).
    """
    import jax

    from acg_tpu.io.mtxfile import finalize_vector_file, write_vector_window
    from acg_tpu.parallel.multihost import is_primary

    prob = getattr(solver, "problem", None)
    if prob is None:
        prob = solver.inner.problem  # RefinedSolver wrapper (--refine)
    bounds = prob.band_bounds
    windows = []  # (row_lo, values) for this controller's parts
    wrc = 0
    try:
        if isinstance(x_st, np.ndarray):
            # refined path: the outer iteration returns a host f64
            # global vector; every controller still writes ONLY its
            # owned windows
            for p in prob.owned_parts:
                lo, hi = int(bounds[p]), int(bounds[p + 1])
                windows.append((lo, np.asarray(x_st[lo:hi], np.float64)))
        else:
            seen = set()
            for sh in x_st.addressable_shards:
                data = np.asarray(sh.data)
                sl = sh.index[0]
                start = (int(sl.start or 0) if isinstance(sl, slice)
                         else int(sl))
                for j in range(data.shape[0]):
                    p = start + j
                    s = prob.subs[p]
                    if p in seen or s is None or s.A_local is None:
                        continue  # stub/duplicate row on this device
                    seen.add(p)
                    windows.append((int(bounds[p]),
                                    data[j, : s.nowned]
                                    .astype(np.float64)))
        t0 = time.perf_counter()
        for lo, vals in windows:
            write_vector_window(args.output, n, lo, vals)
        _log(args, f"range-write {len(windows)} owned windows:", t0)
        args._phases.add("writeback", time.perf_counter() - t0)
    except OSError as e:
        sys.stderr.write(f"acg-tpu: {args.output}: {e}\n")
        wrc = 1
    rc = _stage_sync(args, "write", wrc)
    if rc:
        if not wrc:
            sys.stderr.write("acg-tpu: aborting: a peer controller "
                             "failed during the solution write\n")
        return rc

    # manufactured error norms without a gather: per-controller partial
    # sums over owned windows, combined across controllers
    err = None
    if xsol is not None:
        part_sq = sum(float(np.sum((vals - xsol[lo:lo + vals.size]) ** 2))
                      for lo, vals in windows)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            part_sq = float(np.sum(multihost_utils.process_allgather(
                np.float64(part_sq), tiled=False)))
        err = np.sqrt(part_sq)

    _fold_phases(args, solver)
    if not is_primary():
        return 0
    finalize_vector_file(args.output, n)
    # permuted inputs (mtx2bin --partition) keep their on-disk ordering
    # in the range-written output -- un-permuting would scatter every
    # window and defeat the no-gather design.  Make the file
    # self-describing: copy the perm sidecar next to it and say so.
    import os
    perm_path = args.A + ".perm.mtx"
    if os.path.exists(perm_path):
        import shutil
        shutil.copyfile(perm_path, args.output + ".perm.mtx")
        sys.stderr.write(
            f"acg-tpu: note: {args.output} is in the matrix's permuted "
            f"row ordering; {args.output}.perm.mtx (copied) maps rows "
            f"back to the original numbering\n")
    solver.stats.fwrite(sys.stderr)
    if err is not None:
        sys.stderr.write(f"initial error 2-norm: "
                         f"{np.linalg.norm(xsol):.15g}\n")
        sys.stderr.write(f"error 2-norm: {err:.15g}\n")
    return 0


def _emit_solution(args, x, perm=None) -> None:
    """Solution output policy, uniform across paths: ``--output FILE``
    writes a binary array vector (the same layout the distributed write
    assembles -- readable with ``read_mtx(binary=True)``), regardless
    of ``--quiet``; otherwise the text form goes to stdout unless
    ``--quiet``.  ``perm`` (a permuted-to-original row map) is applied
    first so users always see their own ordering."""
    if args.output is None and args.quiet:
        return
    from acg_tpu.io.mtxfile import multi_vector_mtx, vector_mtx, write_mtx

    x = np.asarray(x)
    if perm is not None:
        xo = np.empty_like(x)
        xo[perm] = x
        x = xo
    # batched solutions are (n, B) column blocks: one dense array file
    # with B columns (io.mtxfile.vector_columns reads it back)
    wrap = (multi_vector_mtx if x.ndim == 2 and x.shape[1] > 1
            else lambda v: vector_mtx(np.asarray(v).reshape(-1)))
    if args.output is not None:
        write_mtx(args.output, wrap(np.asarray(x, np.float64)),
                  binary=True)
    elif not args.quiet:
        write_mtx(sys.stdout.buffer, wrap(x), numfmt=args.numfmt)


def _load_perm_sidecar(matrix_path: str, n: int):
    """The permuted-to-original row map written by ``mtx2bin
    --partition``, or None.  A sidecar whose size disagrees with the
    matrix is STALE (e.g. the matrix was regenerated for a different
    size at the same path) -- fail loudly rather than scramble output."""
    import os

    from acg_tpu.io.mtxfile import read_mtx

    path = matrix_path + ".perm.mtx"
    if not os.path.exists(path):
        return None
    perm = np.asarray(read_mtx(path, binary=True).vals
                      ).reshape(-1).astype(np.int64) - 1
    if perm.size != n or (np.sort(perm) != np.arange(n)).any():
        raise SystemExit(
            f"acg-tpu: {path} is not a permutation of {n} rows -- stale "
            f"sidecar from an earlier mtx2bin run?  Regenerate with "
            f"mtx2bin --expand [--partition] or delete it")
    return perm


def _solve_generated_sharded(args, dim, n, N, jax, jnp, dtype,
                             vec_dtype) -> int:
    """Sharded gen-direct path: assembly and solve over the device mesh
    (``parallel/sharded_dia``).  Runs identically single-controller and
    under ``--multihost`` -- every array is born sharded, so controllers
    never hold host copies (the role of the reference's root-read +
    subgraph scatter, ``graph.c:1529-1897``, with the scatter deleted
    rather than ported)."""
    import numpy as np

    from acg_tpu.errors import (AcgError, BreakdownError,
                                NotConvergedError)
    from acg_tpu.io.mtxfile import vector_mtx, write_mtx
    from acg_tpu.parallel.multihost import get_global, is_primary
    from acg_tpu.parallel.sharded_dia import (build_sharded_poisson_solver,
                                              spot_check_manufactured)
    from acg_tpu.solvers import StoppingCriteria

    if args.profile_ops is not None:
        raise SystemExit(
            "acg-tpu: --profile-ops is not available on the sharded "
            "direct-assembly path (single-chip: drop --nparts/"
            "--manufactured-solution)")
    if (args.refine and args.dtype not in ("f32", "mixed")
            and not (args.dtype == "bf16" and args.replace_every)):
        # the natural rtol-1e-9 nest for bf16 storage: replacement-inner
        # (sound bf16 CG) + df64-refine-outer -- solve_refined's inner
        # calls route through JaxCGSolver.solve, which dispatches to the
        # replacement program whenever replace_every is set
        raise SystemExit(
            "acg-tpu: sharded --refine runs df64 outer residuals over "
            "f32 inner solves; use --dtype f32/mixed, or --dtype bf16 "
            "with --replace-every (sound-bf16 inner solves)")
    if args.kernels == "fused":
        raise SystemExit(
            "acg-tpu: the sharded direct-assembly path supports "
            "--kernels auto/xla (roll formulation) or pallas (per-shard "
            "clustered kernel + ppermute halo); 'fused' rides the "
            "single-device and explicit-mesh (--nparts) tiers")
    sharded_kernels = ("pallas-roll" if args.kernels == "pallas"
                       else "xla-roll")
    if args.replace_every and (args.diff_atol > 0 or args.diff_rtol > 0):
        raise SystemExit(
            "acg-tpu: --replace-every supports residual criteria only "
            "(--diff-atol/--diff-rtol have no meaning across "
            "replacement segments)")

    nparts = args.nparts or len(jax.devices())
    t0 = time.perf_counter()
    try:
        solver = build_sharded_poisson_solver(
            n, dim, nparts=nparts, dtype=dtype, vector_dtype=vec_dtype,
            pipelined="pipelined" in args.solver,
            precise_dots=args.precise_dots, epsilon=args.epsilon,
            replace_every=args.replace_every, kernels=sharded_kernels,
            recovery=getattr(args, "_recovery", None),
            trace=args._trace, progress=args.progress,
            precond=getattr(args, "_precond", None),
            health=getattr(args, "_health", None),
            ckpt=getattr(args, "_ckpt", None),
            algorithm=getattr(args, "_algorithm", None))
    except ValueError as e:
        raise SystemExit(f"acg-tpu: {e}")
    _log(args, f"assemble sharded DIA planes on device ({nparts} parts):",
         t0)
    args._phases.add("ingest", time.perf_counter() - t0)

    xsol = None
    if args.manufactured_solution:
        t0 = time.perf_counter()
        if args.refine:
            # b in double-float: an f32-rounded b would cap the
            # reachable error at ~1e-7 regardless of solver accuracy
            xsol, b = solver.manufactured_df(seed=args.seed)
        else:
            xsol, b = solver.manufactured(seed=args.seed)
        _log(args, "manufactured solution (on device):", t0)
        if solver.stencil is not None:
            # independent oracle: analytic stencil rows recomputed on
            # the host (shares NOTHING with the solve's SpMV).  The
            # acceptance threshold follows the dtype b is STORED in:
            # bf16 b is rounded to 8 mantissa bits by construction
            # (measured max rel dev 3.3e-3 vs 5.8e-8 for f32), which is
            # storage, not a manufacturing bug (round-4 advisor finding)
            bh_ = b[0] if isinstance(b, tuple) else b
            tol = 1e-2 if bh_.dtype == jnp.bfloat16 else 1e-5
            dev = spot_check_manufactured(solver, xsol, b)
            sys.stderr.write(f"manufactured-b spot check (analytic "
                             f"stencil rows): max rel dev {dev:.3e}\n")
            if not dev < tol:
                sys.stderr.write("acg-tpu: manufactured b FAILED the "
                                 "independent spot check\n")
                _stage_sync(args, "solve", 1)
                return 1
    else:
        b = solver.ones_b()

    criteria = StoppingCriteria(
        maxits=args.max_iterations,
        residual_atol=args.residual_atol, residual_rtol=args.residual_rtol,
        diff_atol=args.diff_atol, diff_rtol=args.diff_rtol)
    t0 = time.perf_counter()
    from acg_tpu.tracing import profiler_trace
    with profiler_trace(args.trace):
        try:
            # device-resident result: the gather to host happens only
            # when the solution is actually written
            if args.refine:
                xh, xl = solver.solve_refined(
                    b, criteria=criteria, inner_rtol=args.refine_rtol,
                    inner_maxits=args.refine_inner_maxits,
                    warmup=args.warmup)
                x = xh
            else:
                x = _run_solve(args, solver, b, criteria=criteria,
                               warmup=args.warmup, host_result=False)
                xl = None
        except (NotConvergedError, BreakdownError) as e:
            # the stats block carries the resilience event log -- most
            # needed exactly when recovery failed
            sys.stderr.write(f"acg-tpu: {e}\n")
            _fold_phases(args, solver)
            if is_primary():
                solver.stats.fwrite(sys.stderr)
            _emit_telemetry(args, solver, matrix_id=args.A,
                            nparts=nparts, collective=False)
            _stage_sync(args, "solve", 1)
            return 1
        except AcgError as e:
            # solve-time configuration refusals (e.g. replace_every + an
            # armed fault injector) carry typed AcgErrors
            sys.stderr.write(f"acg-tpu: {e}\n")
            _stage_sync(args, "solve", 1)
            return 1
    _attach_trace_analysis(args, solver)
    _log(args, "solve:", t0)
    rc = _stage_sync(args, "solve", 0)
    if rc:
        sys.stderr.write("acg-tpu: aborting: a peer controller failed "
                         "during the solve\n")
        return rc

    # cross-process COLLECTIVE steps run on every controller BEFORE the
    # primary-only output gate: a non-primary process returning early
    # while the primary still waits in an error-norm reduction or the
    # solution allgather would deadlock the pod
    if xsol is None:
        errs = None
    elif xl is not None:
        errs = solver.error_norms_df(x, xl, xsol)
    else:
        errs = solver.error_norms(x, xsol)
    want_x = not args.quiet or args.output is not None
    x_host = None
    if want_x:
        if xl is not None:
            # refined solutions live as a df64 (hi, lo) pair; emitting
            # only the f32 hi part would silently discard the accuracy
            # --refine just computed (~1e-7 vs the reported ~1e-9)
            x_host = (np.asarray(get_global(x), np.float64)
                      + np.asarray(get_global(xl), np.float64))
        else:
            x_host = np.asarray(get_global(x))

    _fold_phases(args, solver)
    if not is_primary():
        _emit_telemetry(args, solver, matrix_id=args.A, nparts=nparts)
        return 0
    solver.stats.fwrite(sys.stderr)
    if errs is not None:
        sys.stderr.write(f"initial error 2-norm: {errs[0]:.15g}\n")
        sys.stderr.write(f"error 2-norm: {errs[1]:.15g}\n")
    t_wb = time.perf_counter()
    if x_host is not None:
        _emit_solution(args, x_host)
    args._phases.add("writeback", time.perf_counter() - t_wb)
    _emit_telemetry(args, solver, matrix_id=args.A, nparts=nparts)
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if "--buildinfo" in argv:
        try:
            return _buildinfo(sys.stdout)
        except BrokenPipeError:
            # stdout consumer (head, grep -m) closed early.  Complete
            # the SIGPIPE recipe: the interpreter flushes sys.stdout
            # once more at exit, and with the pipe still broken that
            # flush would print an "Exception ignored" traceback AFTER
            # this clean return -- point the fd at devnull so it cannot
            import os
            os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
            return 0
    args = make_parser().parse_args(argv)
    if args.serve:
        # solver-service mode (acg_tpu.serve): the daemon owns its own
        # lifecycle (metrics/observatory arming, signal-driven
        # teardown); --supervise/--chaos wrap the LIVE daemon instead
        # of batch children
        from acg_tpu.serve import run_serve
        return run_serve(args, list(argv))
    if args.chaos is not None or args.supervise:
        # elastic-recovery driver modes (acg_tpu.supervisor): the
        # supervisor owns the child solve processes' lifecycle; none of
        # the in-process teardown below (fault env, metrics/observatory
        # finalisation) applies to the supervising parent
        from acg_tpu.supervisor import run_chaos, run_supervised
        return (run_chaos(args, list(argv)) if args.chaos is not None
                else run_supervised(args, list(argv)))
    args.numfmt = _validate_numfmt(args.numfmt)
    import os

    from acg_tpu import faults
    prev_fault_env = os.environ.get(faults.ENV_VAR)
    try:
        rc = _main(args)
        if rc == 0 and getattr(args, "_soak_report", None) is not None:
            # the --fail-on-drift gate: a clean solve run whose latency
            # drifted is a service-level failure (exit 7)
            from acg_tpu.soak import gate_exit_code
            rc = gate_exit_code(args._soak_report, args.fail_on_drift)
        if rc == 0 and args.fail_on_slo:
            # the --fail-on-slo gate: a clean run that breached a
            # declared objective is a service-level failure (exit 8)
            from acg_tpu import observatory
            rc = observatory.slo_exit_code(True)
        return rc
    except OSError as e:
        sys.stderr.write(f"acg-tpu: {e}\n")
        return 1
    finally:
        if args.metrics_file and getattr(args, "_metrics_armed", False):
            # the atexit/SIGTERM handlers cover process death; this
            # covers in-process callers (tests, library use) AND makes
            # sure error paths leave a final scrape behind.  Gated on
            # _main having armed the layer: a run that died in flag
            # validation ran nothing, and an all-zeros scrape must not
            # clobber the last healthy run's textfile
            from acg_tpu import metrics
            try:
                metrics.write_textfile(args.metrics_file)
            except OSError as e:
                sys.stderr.write(
                    f"acg-tpu: --metrics-file {args.metrics_file}: "
                    f"{e}\n")
        if args.timeline:
            # the span recorder is process-wide, scoped to THIS
            # invocation (the faults-install discipline): disarm AND
            # clear so in-process callers never leak spans across runs
            from acg_tpu import tracing
            tracing.disarm()
        if getattr(args, "_observatory_armed", False):
            # final --status-file flush (solve marked over) then disarm
            # AND clear -- the status recorder and SLO state are
            # process-wide, scoped to THIS invocation (the tracing
            # discipline); the gate above already read the verdict
            from acg_tpu import observatory
            observatory.shutdown()
        if args.fault_inject:
            # _main exports the spec (env var = how children inherit it)
            # and installs it process-wide; both are scoped to THIS
            # invocation -- in-process callers (tests, library use) must
            # not stay armed after main returns
            faults.install(None)
            if prev_fault_env is None:
                os.environ.pop(faults.ENV_VAR, None)
            else:
                os.environ[faults.ENV_VAR] = prev_fault_env


def _main(args) -> int:

    # stage 0: runtime init (the MPI/NCCL/NVSHMEM init stage)
    import os

    # telemetry tier: the always-on phase timer (ingest -> partition ->
    # transfer -> compile -> solve -> writeback, reported in the stats
    # block's timings: section), and the in-loop trace/progress knobs
    from acg_tpu.telemetry import PhaseTimer
    args._phases = PhaseTimer()
    # timeline tier (acg_tpu.tracing): arm the span recorder BEFORE the
    # first phase runs so ingest/partition land on the timeline; scoped
    # to this invocation (main() disarms in its finally)
    if args.timeline:
        from acg_tpu import tracing
        tracing.arm()
        args._timeline_written = False
    if args.explain:
        # refuse incompatible modes BEFORE anything expensive or
        # blocking runs: multihost init would block waiting for peers,
        # and an armed fault injector would poison the timed analysis
        # solves while the lowered programs stay pristine -- the report
        # would describe a solve that never runs
        if (args.multihost or args.coordinator is not None
                or args.distributed_read):
            raise SystemExit(
                "acg-tpu: --explain is a single-controller analysis "
                "pass (drop --multihost/--coordinator/"
                "--distributed-read)")
        if args.fault_inject or os.environ.get("ACG_TPU_FAULT_INJECT"):
            raise SystemExit(
                "acg-tpu: --explain analyses and times the PRISTINE "
                "solve programs; drop --fault-inject (fault-test a "
                "normal solve instead)")
        # output-bearing solve flags refuse explicitly rather than
        # silently produce nothing (the telemetry-tier convention):
        # --explain runs its own short analysis solves, so none of
        # these sinks would be written
        ignored = [flag for flag, on in [
            ("--convergence-log", bool(args.convergence_log)),
            ("--progress", args.progress > 0),
            ("-o/--output", args.output is not None),
            ("--profile-ops", args.profile_ops is not None),
            ("--output-comm-matrix", args.output_comm_matrix),
            ("--audit-every (--explain computes its own convergence "
             "verdict from the host oracle)", args.audit_every > 0),
            ("--stall-window", args.stall_window > 0),
            ("--timeline (the analysis solves are not the pipeline "
             "the timeline describes; --trace works and feeds the "
             "measured verdict)", args.timeline is not None),
            ("--status-port/--status-file (the analysis solves are "
             "not the solve a status plane watches)",
             args.status_port > 0 or args.status_file is not None),
            ("--history (the ledger records solves, not analysis "
             "passes; --explain --plan --history consults it "
             "read-only)",
             args.history is not None and args.plan is None),
            ("--slo (objectives judge real solves)",
             args.slo is not None),
        ] if on]
        if ignored:
            raise SystemExit(
                f"acg-tpu: --explain is an analysis pass and produces "
                f"none of: {', '.join(ignored)} -- run a normal solve "
                f"for those (--stats-json works with --explain)")
    # communication observatory (acg_tpu.commbench): validate the
    # calibration source BEFORE anything expensive (the explain/fault
    # discipline), refuse configurations the observatory could never
    # honestly measure
    if args.commbench is not None and args.calibration is not None:
        raise SystemExit(
            "acg-tpu: --commbench and --calibration are two calibration "
            "sources; run --commbench to produce a document, then "
            "--explain --calibration FILE to consume it")
    if args.commbench is not None:
        if (args.multihost or args.coordinator is not None
                or args.distributed_read):
            raise SystemExit(
                "acg-tpu: --commbench is a single-controller "
                "measurement pass (drop --multihost/--coordinator/"
                "--distributed-read)")
        if args.fault_inject or os.environ.get("ACG_TPU_FAULT_INJECT"):
            raise SystemExit(
                "acg-tpu: --commbench measures the PRISTINE mesh "
                "collectives; drop --fault-inject")
        if not args.explain:
            ignored = [flag for flag, on in [
                ("--convergence-log", bool(args.convergence_log)),
                ("-o/--output", args.output is not None),
                ("--profile-ops", args.profile_ops is not None),
                ("--timeline", args.timeline is not None),
                ("--stats-json (the commbench document IS the "
                 "structured output; --stats-json works with "
                 "--explain --commbench)", args.stats_json is not None),
                ("--soak", args.soak > 0),
                ("--history (the ledger records solves, not "
                 "microbenchmarks)", args.history is not None),
                ("--slo (objectives judge real solves)",
                 args.slo is not None),
            ] if on]
            if ignored:
                raise SystemExit(
                    f"acg-tpu: --commbench is a measurement pass and "
                    f"produces none of: {', '.join(ignored)} -- run a "
                    f"normal solve for those")
    if args.calibration is not None:
        from acg_tpu.commbench import load_calibration
        try:
            args._calibration = load_calibration(args.calibration)
        except OSError as e:
            raise SystemExit(f"acg-tpu: --calibration "
                             f"{args.calibration}: {e}")
        except ValueError as e:
            raise SystemExit(f"acg-tpu: --calibration "
                             f"{args.calibration}: {e}")
        args._calibration_source = f"--calibration {args.calibration}"
    if args.telemetry_window <= 0:
        raise SystemExit("acg-tpu: --telemetry-window must be positive")
    if args.progress < 0:
        raise SystemExit("acg-tpu: --progress must be >= 0")
    # preconditioning tier (acg_tpu.precond): validate the spec BEFORE
    # anything expensive, and refuse configurations where the armed
    # preconditioner could never run (the fault-injector discipline)
    from acg_tpu.precond import parse_precond
    try:
        args._precond = parse_precond(args.precond)
    except ValueError as e:
        raise SystemExit(f"acg-tpu: {e}")
    if args._precond is not None:
        unsupported = [flag for flag, on in [
            (f"--solver {args.solver} (the external oracles have no "
             f"preconditioner hooks)",
             args.solver in ("host-native", "petsc")),
            ("--replace-every (the replacement segments restructure "
             "the recurrences M^-1 threads through)",
             args.replace_every > 0),
            ("--kernels fused (the two-phase kernels fold the whole "
             "iteration; no preconditioner hook)",
             args.kernels == "fused"),
        ] if on]
        if unsupported:
            raise SystemExit(
                f"acg-tpu: --precond {args.precond} does not support: "
                f"{', '.join(unsupported)}")
    # communication-avoiding recurrence selection (acg_tpu.recurrence):
    # validate BEFORE anything expensive, refuse hosts/tiers the armed
    # recurrence could never ride (the fault-injector discipline)
    from acg_tpu.recurrence import parse_algorithm
    try:
        args._algorithm = parse_algorithm(args.algorithm)
    except ValueError as e:
        raise SystemExit(f"acg-tpu: {e}")
    if (args._algorithm is not None
            and not args._algorithm.communication_avoiding):
        # classic/pipelined resolve onto the existing solver names
        if args._algorithm.kind == "pipelined" \
                and args.solver == "acg":
            args.solver = "acg-pipelined"
        elif args._algorithm.kind == "classic" \
                and args.solver == "acg-pipelined":
            args.solver = "acg"
        args._algorithm = None
    if args._algorithm is not None:
        ca = str(args._algorithm)
        unsupported = [flag for flag, on in [
            (f"--solver {args.solver} (the host/external oracles run "
             f"the classic recurrence)",
             args.solver in ("host", "host-native", "petsc")),
            ("--nrhs/--block-cg (no batched CA recurrences yet)",
             args.nrhs >= 2 or args.block_cg),
            ("--refine", args.refine),
            ("--replace-every", args.replace_every > 0),
            ("--precise-dots", args.precise_dots),
            (f"--precond {args.precond} (the CA recurrences run "
             f"unpreconditioned)", args._precond is not None),
            ("--kernels fused", args.kernels == "fused"),
            ("--explain (the explain sweep drives the "
             "classic/pipelined tiers)", args.explain),
            ("--profile-ops (the replay census has no CA op map)",
             args.profile_ops is not None),
            ("--diff-atol/--diff-rtol (residual criteria only)",
             args.diff_atol > 0 or args.diff_rtol > 0),
        ] if on]
        if unsupported:
            raise SystemExit(
                f"acg-tpu: --algorithm {ca} does not support: "
                f"{', '.join(unsupported)}")
    # decision observatory (acg_tpu.planner): validate BEFORE anything
    # expensive.  --autotune owns the axes it plans over -- a flag that
    # pins one of them would make the "decision" a lie, so those refuse
    # rather than silently win
    if args.autotune:
        if args.explain:
            raise SystemExit(
                "acg-tpu: --autotune dispatches a real solve; use "
                "--explain --plan for the ranked table without solving")
        if args.commbench is not None:
            raise SystemExit(
                "acg-tpu: --autotune consumes a SAVED calibration "
                "(--calibration FILE); run --commbench first")
        unsupported = [flag for flag, on in [
            (f"--algorithm {args.algorithm} (the planner chooses the "
             f"recurrence numerically)",
             args.algorithm not in (None, "auto")),
            (f"--solver {args.solver} (the planner chooses among the "
             f"device tiers)", args.solver != "acg"),
            ("--kernels fused (the planner chooses the kernel tier)",
             args.kernels == "fused"),
            ("--nrhs/--block-cg (no batched candidate pricing yet)",
             args.nrhs >= 2 or args.block_cg),
            ("--refine", args.refine),
            ("--replace-every", args.replace_every > 0),
            ("--fault-inject (probes must time the pristine "
             "programs)", bool(args.fault_inject)
             or bool(os.environ.get("ACG_TPU_FAULT_INJECT"))),
            ("--multihost/--coordinator/--distributed-read (single-"
             "controller planning only)", args.multihost
             or args.coordinator is not None or args.distributed_read),
        ] if on]
        if unsupported:
            raise SystemExit(
                f"acg-tpu: --autotune does not support: "
                f"{', '.join(unsupported)}")
    if args.plan is not None and not (args.explain or args.autotune):
        raise SystemExit(
            "acg-tpu: --plan needs --explain (ranked table, no solve) "
            "or --autotune (plan, probe, dispatch)")
    # numerical-health tier (acg_tpu.health): validate the spec BEFORE
    # anything expensive; refuse configurations where an armed audit
    # could never run (the fault-injector / precond discipline)
    from acg_tpu import health as _health_mod
    if args.gap_threshold and not args.audit_every:
        raise SystemExit(
            "acg-tpu: --gap-threshold needs --audit-every K (the "
            "threshold judges audit gaps; without an audit it could "
            "never fire)")
    if args.abft and not args.audit_every:
        raise SystemExit(
            "acg-tpu: --abft fires the checksum test at the audit "
            "cadence; add --audit-every K")
    try:
        args._health = _health_mod.make_spec(
            args.audit_every, args.gap_threshold, args.on_gap,
            args.stall_window, abft=args.abft,
            abft_threshold=args.abft_threshold)
    except ValueError as e:
        raise SystemExit(f"acg-tpu: {e}")
    if args._health is not None:
        unsupported = [flag for flag, on in [
            (f"--solver {args.solver} (the external oracles have no "
             f"audit hooks)",
             args.solver in ("host-native", "petsc")),
            ("--replace-every (the replacement segments already "
             "recompute b - Ax every K iterations)",
             args.replace_every > 0),
            ("--kernels fused (the two-phase kernels fold the whole "
             "iteration; no audit hook)", args.kernels == "fused"),
            ("--refine (the refinement outer loop already recomputes "
             "f64 true residuals every pass)", args.refine),
        ] if on]
        if unsupported:
            raise SystemExit(
                f"acg-tpu: --audit-every/--stall-window do not "
                f"support: {', '.join(unsupported)}")
    # survivability tier (acg_tpu.checkpoint): validate + load the
    # resume snapshot BEFORE anything expensive (a corrupted or
    # mismatched file must refuse here, not after a multi-second
    # compile), and refuse configurations the chunk drivers cannot
    # serve (the fault-injector could-never-fire discipline)
    args._ckpt = None
    if args.ckpt_every > 0 and args.ckpt_secs > 0:
        raise SystemExit("acg-tpu: --ckpt-every and --ckpt-secs are "
                         "mutually exclusive cadences; pick one")
    if args.ckpt_secs < 0:
        raise SystemExit("acg-tpu: --ckpt-secs must be positive "
                         "seconds")
    if args.ckpt is not None and args.ckpt_every <= 0 \
            and args.ckpt_secs <= 0:
        raise SystemExit("acg-tpu: --ckpt needs a snapshot cadence: "
                         "add --ckpt-every K or --ckpt-secs S")
    if (args.ckpt_every or args.ckpt_secs > 0) and args.ckpt is None:
        raise SystemExit("acg-tpu: --ckpt-every/--ckpt-secs need "
                         "--ckpt FILE (a cadence with nowhere to "
                         "write)")
    if args.resume_repartition and args.resume is None:
        raise SystemExit("acg-tpu: --resume-repartition is a resume "
                         "policy; add --resume FILE")
    if args.heartbeat < 0:
        raise SystemExit("acg-tpu: --heartbeat must be >= 0 seconds")
    if 0 < args.heartbeat <= 0.5:
        # the beat period is floored at 0.5 s (coordinator-KV write
        # cost) and the deadline must exceed the period
        raise SystemExit("acg-tpu: --heartbeat deadlines this short "
                         "cannot be served (beat period is floored at "
                         "0.5 s); use > 0.5 seconds")
    if args.ckpt is not None or args.resume is not None:
        unsupported = [flag for flag, on in [
            (f"--solver {args.solver} (the external oracles expose no "
             f"loop carry)", args.solver in ("host-native", "petsc")),
            ("--replace-every (the replacement segments' inner state "
             "never leaves the program)", args.replace_every > 0),
            ("--kernels fused (the two-phase kernels expose no loop "
             "carry)", args.kernels == "fused"),
            ("--refine (the refinement outer loop re-enters solve; "
             "checkpoint the inner tolerance solve instead)",
             args.refine),
            ("--explain (an analysis pass; nothing to snapshot)",
             args.explain),
            ("--diff-atol/--diff-rtol (the dx scalar is not part of "
             "the snapshot carry)",
             args.diff_atol > 0 or args.diff_rtol > 0),
            # --ckpt+--soak is fine (snapshots carry across the
            # repetitions; serialisation bills to its own phase, so
            # the latency histograms stay clean) -- but --resume would
            # re-enter EVERY repetition from the same snapshot
            ("--soak with --resume (every repetition would re-resume "
             "from the same snapshot; resume the solve once, then "
             "soak)", args.soak > 0 and args.resume is not None),
        ] if on]
        if unsupported:
            raise SystemExit(
                f"acg-tpu: --ckpt/--resume do not support: "
                f"{', '.join(unsupported)}")
        from acg_tpu.checkpoint import CheckpointConfig, load_snapshot
        resume_snap = None
        if args.resume is not None:
            from acg_tpu.errors import AcgError as _AcgError
            try:
                resume_snap = load_snapshot(args.resume)
            except _AcgError as e:
                raise SystemExit(f"acg-tpu: {e}")
        try:
            args._ckpt = CheckpointConfig(
                path=args.ckpt, every=args.ckpt_every,
                secs=args.ckpt_secs, resume=resume_snap,
                repartition=args.resume_repartition)
        except ValueError as e:
            raise SystemExit(f"acg-tpu: {e}")
    # batched multi-RHS tier (acg_tpu.solvers.batched): validate the
    # selection BEFORE anything expensive, refuse configurations the
    # batched programs cannot serve (the fault-injector could-never-
    # fire discipline).  --nrhs 1 and flag-absent take the UNBATCHED
    # path -- byte-identical programs (the disarmed-identity contract)
    if args.nrhs < 0:
        raise SystemExit("acg-tpu: --nrhs must be >= 0")
    if args.block_cg and args.nrhs < 2:
        raise SystemExit(
            "acg-tpu: --block-cg shares one Krylov block across B "
            "right-hand sides; add --nrhs B (B >= 2)")
    args._batched = args.nrhs >= 2
    if args._batched:
        unsupported = [flag for flag, on in [
            (f"--solver {args.solver} (use the device solvers; the "
             f"host batched oracle is a library API)",
             args.solver in ("host", "host-native", "petsc")),
            ("--refine", args.refine),
            ("--replace-every", args.replace_every > 0),
            (f"--kernels {args.kernels} (batched runs the XLA "
             f"multi-vector SpMV)", args.kernels in ("pallas", "fused")),
            ("--audit-every/--stall-window (no batched audit hooks "
             "yet)", args._health is not None),
            ("--fault-inject/--recover (no batched breakdown "
             "detection yet)", bool(args.fault_inject) or args.recover),
            ("--comm dma (the batched mesh tier runs the XLA "
             "all_to_all transport)", args.comm in ("dma", "nvshmem")),
            ("--progress (no batched heartbeat hook yet; "
             "--status-file/--status-port serve per-RHS progress)",
             args.progress > 0),
            ("--diff-atol/--diff-rtol (residual criteria only)",
             args.diff_atol > 0 or args.diff_rtol > 0),
            ("--multihost/--coordinator (single-controller tier)",
             args.multihost or args.coordinator is not None),
            ("--distributed-read", args.distributed_read),
            ("--profile-ops", args.profile_ops is not None),
            ("--explain", args.explain),
            ("--output-comm-matrix", args.output_comm_matrix),
        ] if on]
        if unsupported:
            raise SystemExit(
                f"acg-tpu: --nrhs {args.nrhs} does not support: "
                f"{', '.join(unsupported)}")
    # matrix-free operator tier (acg_tpu.ops.operator): validate the
    # spec BEFORE anything expensive, refuse configurations the armed
    # operator could never serve (the fault-injector could-never-fire
    # discipline).  'none' takes the assembled path -- byte-identical
    # dispatched programs (the disarmed-identity contract)
    from acg_tpu.ops.operator import parse_operator_spec
    try:
        args._operator_spec = parse_operator_spec(args.operator)
    except ValueError as e:
        raise SystemExit(f"acg-tpu: {e}")
    args._operator_id = None
    if args._operator_spec is not None:
        unsupported = [flag for flag, on in [
            (f"--solver {args.solver} (the host/external oracles run "
             f"assembled matrices)",
             args.solver in ("host", "host-native", "petsc")),
            (f"--dtype {args.dtype} (operators generate plane values "
             f"in the storage dtype; bf16 has no matrix traffic left "
             f"to halve)", args.dtype in ("bf16", "mixed")),
            (f"--spmv-format {args.spmv_format} (forcing an assembled "
             f"device format contradicts matrix-free)",
             args.spmv_format != "auto"),
            ("--replace-every (the bf16 tier's contract; operators "
             "run f32/f64)", args.replace_every > 0),
            ("--refine", args.refine),
            ("--block-cg (the block-Gram tier keeps assembled "
             "matrices)", args.block_cg),
            ("--nrhs on the mesh (the batched dist tier keeps "
             "assembled local blocks; --nrhs rides matrix-free on the "
             "single-device tier: --comm none / --nparts 1)",
             args._batched and not (args.comm == "none"
                                    or args.nparts == 1)),
            ("--epsilon (the stencil computes the UNshifted system; a "
             "shifted solve needs the assembled path)",
             bool(args.epsilon)),
            ("--multihost/--coordinator (single-controller tier)",
             args.multihost or args.coordinator is not None),
            ("--distributed-read", args.distributed_read),
        ] if on]
        if unsupported:
            raise SystemExit(
                f"acg-tpu: --operator {args.operator} does not "
                f"support: {', '.join(unsupported)}")
        if (args._operator_spec[0] in ("auto", "poisson", "aniso2d")
                and not args.A.startswith("gen:")):
            raise SystemExit(
                "acg-tpu: --operator stencil* pairs with a gen: matrix "
                "spec (a file matrix is assembled by definition and "
                "the stencil could silently compute a different "
                "system); register a user:NAME operator for "
                "file-backed systems")
    if args.aniso is not None:
        if not 0.0 < args.aniso <= 1.0:
            raise SystemExit("acg-tpu: --aniso EPS must be in (0, 1]")
        if not (args.A.startswith("gen:poisson2d:")):
            raise SystemExit(
                "acg-tpu: --aniso generates the stretched-grid 2D "
                "Poisson family and needs a gen:poisson2d:N matrix "
                "spec")
    # service-metrics tier: validate + arm BEFORE anything records.
    # --soak implies arming (the soak driver reports from the registry
    # histograms); --metrics-file/--metrics-port arm it for single
    # solves too
    if args.soak < 0:
        raise SystemExit("acg-tpu: --soak must be >= 0")
    if args.fail_on_drift is not None and not args.soak:
        raise SystemExit("acg-tpu: --fail-on-drift needs --soak N "
                         "(drift is a property of repeated solves)")
    if args.fail_on_drift is not None and args.fail_on_drift <= 0:
        # a zero/negative threshold trips on ordinary jitter -- a
        # "gate" that fails healthy runs
        raise SystemExit("acg-tpu: --fail-on-drift must be positive "
                         "percent")
    if args.fail_on_drift is not None:
        from acg_tpu.soak import gate_is_vacuous
        if gate_is_vacuous(args.soak):
            # the baseline window would consume the whole run: a gate
            # that inspects nothing must refuse, not green CI silently
            raise SystemExit(
                f"acg-tpu: --fail-on-drift is vacuous at --soak "
                f"{args.soak}: the baseline window consumes the whole "
                f"run; use --soak 4 or more")
    if args.metrics_port < 0 or args.metrics_port > 65535:
        raise SystemExit("acg-tpu: --metrics-port must be 0-65535")
    # live-observatory tier (acg_tpu.observatory): validate + arm
    # BEFORE anything records (the metrics-tier discipline)
    from acg_tpu import observatory
    if args.status_port < 0 or args.status_port > 65535:
        raise SystemExit("acg-tpu: --status-port must be 0-65535")
    args._slo = None
    if args.slo is not None:
        try:
            args._slo = observatory.parse_slo(args.slo)
        except ValueError as e:
            raise SystemExit(f"acg-tpu: {e}")
    if args.fail_on_slo and args._slo is None:
        raise SystemExit("acg-tpu: --fail-on-slo needs --slo SPEC "
                         "(a gate with no declared objectives could "
                         "never trip)")
    if (args._slo is not None and args._slo.gap is not None
            and not args.audit_every):
        raise SystemExit("acg-tpu: --slo gap=G judges audit gaps; add "
                         "--audit-every K (without an audit the "
                         "objective could never be observed)")
    if args.history is not None and os.path.isfile(args.history):
        raise SystemExit(f"acg-tpu: --history {args.history} is a "
                         f"file; the ledger needs a directory")
    if args.soak:
        unsupported = [flag for flag, on in [
            ("--refine (the outer iteration re-enters solve itself)",
             args.refine),
            ("--explain (an analysis pass, not a serving loop)",
             args.explain),
            ("--profile-ops", args.profile_ops is not None),
            ("--multihost/--coordinator (soak is per-process; run one "
             "driver per controller)",
             args.multihost or args.coordinator is not None),
            ("--distributed-read", args.distributed_read),
        ] if on]
        if unsupported:
            raise SystemExit(f"acg-tpu: --soak does not support: "
                             f"{', '.join(unsupported)}")
    if (args.metrics_file or args.metrics_port or args.soak
            or args._slo is not None):
        from acg_tpu import metrics
        metrics.arm()
        args._metrics_armed = True
        if args.metrics_file:
            metrics.install_flush_handlers(args.metrics_file)
        if args.metrics_port and args.metrics_port != args.status_port:
            # an equal --status-port serves /metrics itself (one
            # combined endpoint); starting both would fight for the
            # bind
            srv = metrics.serve(args.metrics_port)
            _log(args, f"metrics: serving /metrics on port "
                       f"{srv.server_address[1]}")
    if (args.status_port or args.status_file or args.history
            or args._slo is not None):
        observatory.arm()
        args._observatory_armed = True
        if args._slo is not None:
            observatory.install_slo(args._slo)
        if args.status_file:
            observatory.set_status_file(args.status_file)
        if args.status_port:
            ssrv = observatory.serve_status(args.status_port)
            _log(args, f"status: serving /status (and /metrics) on "
                       f"port {ssrv.server_address[1]}")
    # the ring buffer arms only when the JSONL sink will read it
    # (--stats-json alone stays compatible with every solver tier,
    # including replace_every/fused which refuse in-loop telemetry)
    args._trace = args.telemetry_window if args.convergence_log else 0
    if ((args.convergence_log or args.progress)
            and args.solver in ("host-native", "petsc")):
        sys.stderr.write(
            f"acg-tpu: warning: --convergence-log/--progress have no "
            f"in-loop hooks in --solver {args.solver} (the external "
            f"oracles); --stats-json still works\n")

    # fault injector + recovery policy (the resilience tier), armed
    # BEFORE the backend probe so backend:hang specs actually reach the
    # probe children.  The spec installs process-wide for the in-process
    # solver layers AND exports as the env var -- the env var is how
    # every child (probe, dryrun, multi-controller peers) inherits it
    recovery = None
    env_spec = os.environ.get("ACG_TPU_FAULT_INJECT")
    if env_spec and not args.fault_inject:
        # validate the env-var route EARLY: parsed lazily, a malformed
        # spec would otherwise crash the probe child and be misreported
        # as "backend unavailable"
        from acg_tpu import faults
        try:
            faults.parse_fault_spec(env_spec)
        except ValueError as e:
            raise SystemExit(f"acg-tpu: {faults.ENV_VAR}: {e}")
    if args.fault_inject:
        from acg_tpu import faults
        try:
            spec = faults.parse_fault_spec(args.fault_inject)
            faults.install(spec)
        except ValueError as e:
            raise SystemExit(f"acg-tpu: {e}")
        if spec.site == "solve" and not args.soak:
            # the slowdown site fires from the soak driver's per-solve
            # hook: armed without --soak it could never fire (the
            # replace_every refusal rationale)
            raise SystemExit(
                "acg-tpu: solve:slow fires from the soak driver's "
                "per-solve hook; add --soak N")
        if spec.site == "crash" and (args._ckpt is None
                                     or args._ckpt.path is None):
            # the hard-exit site fires from the checkpoint chunk
            # drivers between snapshot commits: armed without --ckpt
            # (incl. a resume-only relaunch, which writes no further
            # snapshots) it could never fire -- and a crash with no
            # snapshot to resume from proves nothing (same discipline)
            raise SystemExit(
                "acg-tpu: crash:exit fires between snapshot commits; "
                "arm --ckpt FILE --ckpt-every K")
        os.environ[faults.ENV_VAR] = args.fault_inject
        if (faults.device_fault() is not None
                and args.solver in ("host-native", "petsc")):
            # no injection sites in the native/petsc oracles: an armed
            # injector that can never fire must refuse (the
            # replace_every rationale), not report a clean solve
            raise SystemExit(
                f"acg-tpu: --fault-inject has no injection sites in "
                f"--solver {args.solver}; use --solver host or the "
                f"device solvers")

    # stage 0a: BOUNDED backend liveness probe, before anything can
    # touch jax.devices(): the tunneled backend's init has been observed
    # to hang ~15 minutes when the tunnel is down (round 5).  Skipped
    # for plain-CPU platforms, already-initialised processes, and under
    # ACG_TPU_SKIP_BACKEND_PROBE (_platform.backend_probe_needed), so
    # tests and CPU debugging never pay the child-process cost.
    from acg_tpu._platform import backend_probe_needed, probe_backend
    if backend_probe_needed():
        ok, detail = probe_backend()
        if not ok:
            sys.stderr.write(
                f"acg-tpu: backend unavailable: {detail}.  Fix the "
                f"accelerator runtime (or tunnel), run with "
                f"JAX_PLATFORMS=cpu for a host-platform debug solve, or "
                f"set ACG_TPU_SKIP_BACKEND_PROBE=1 to wait out a slow "
                f"init\n")
            from acg_tpu.errors import ExitCode
            return int(ExitCode.BACKEND_UNAVAILABLE)

    # --on-gap replace rides the same recovery machinery as --recover:
    # the gap trip exits through the breakdown path and the driver
    # restarts from the recomputed true residual (the residual-
    # replacement restart), so a policy must exist
    gap_replace = (args._health is not None
                   and args._health.action == "replace")
    if args.recover or args.fault_inject or gap_replace:
        from acg_tpu.solvers.resilience import RecoveryPolicy
        recovery = RecoveryPolicy(max_restarts=max(args.max_restarts, 0),
                                  backoff=max(args.restart_backoff, 0.0),
                                  agree_timeout=args.err_timeout)
        if args.recover and args.solver in ("host-native", "petsc"):
            sys.stderr.write(
                f"acg-tpu: warning: --recover has no effect for "
                f"--solver {args.solver} (the external oracles have no "
                f"breakdown detection)\n")
    args._recovery = recovery

    import jax

    from acg_tpu._platform import honour_jax_platforms
    honour_jax_platforms()
    if args.dtype == "f64":
        jax.config.update("jax_enable_x64", True)
    # persistent compile cache (semantics-neutral; see _platform;
    # disable with ACG_TPU_COMPILE_CACHE=0)
    from acg_tpu._platform import enable_compile_cache
    enable_compile_cache()
    if args.multihost or args.coordinator is not None:
        from acg_tpu.parallel.multihost import initialize
        initialize(args.coordinator, args.num_processes, args.process_id)
        _log(args, f"multihost: process {jax.process_index()} of "
                   f"{jax.process_count()}, {len(jax.local_devices())} local "
                   f"/ {len(jax.devices())} global devices")
        if args.heartbeat > 0:
            # dead-peer detection for the whole run (daemon thread;
            # dies with the process): the stage-sync watchdog cannot
            # see a peer that dies INSIDE the solve collective
            from acg_tpu.parallel.erragree import DeadlineHeartbeat
            args._heartbeat = DeadlineHeartbeat(
                period=max(args.heartbeat / 6.0, 0.5),
                deadline=args.heartbeat).start()
            if getattr(args, "_observatory_armed", False):
                # live-status tier: the status document's peers: block
                # exposes per-controller beat ages from this heartbeat
                observatory.set_heartbeat(args._heartbeat)
    elif args.heartbeat > 0:
        sys.stderr.write("acg-tpu: warning: --heartbeat is "
                         "multi-controller dead-peer detection; no-op "
                         "without --multihost/--coordinator\n")
    import jax.numpy as jnp
    from acg_tpu.errors import (AcgError, BreakdownError,
                                NotConvergedError)
    from acg_tpu.parallel.multihost import is_primary
    from acg_tpu.graph import comm_matrix, partition_matrix
    from acg_tpu.io.mtxfile import MtxFile, read_mtx, write_mtx, vector_mtx
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows
    from acg_tpu.solvers import HostCGSolver, StoppingCriteria
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.refine import RefinedSolver

    # "mixed" splits matrix storage (bf16) from vector storage (f32);
    # every other mode stores both in the named dtype
    if args.dtype == "mixed":
        dtype, vec_dtype = jnp.bfloat16, jnp.float32
    else:
        dtype = {"f64": jnp.float64, "f32": jnp.float32,
                 "bf16": jnp.bfloat16}[args.dtype]
        vec_dtype = dtype
    comm = {"mpi": "xla", "nccl": "xla", "nvshmem": "dma"}.get(args.comm, args.comm)

    if args.commbench is not None and not args.explain:
        # the communication observatory's standalone mode: run the
        # microbenchmark suite over this run's mesh and emit the
        # calibration document (incompatible modes refused at the top
        # of _main, the explain discipline)
        from acg_tpu.commbench import run_commbench
        return run_commbench(args, dtype, vec_dtype)

    if args.explain:
        # the perfmodel tier's analysis pass: per-tier compiled-program
        # introspection + roofline verdict in place of a normal solve
        # (incompatible modes were refused at the top of _main, before
        # the backend probe and multihost init could block)
        if args.commbench is not None:
            # live calibration: collect the commbench document first,
            # then run the explain pass against it (and still write
            # the document when a FILE was named)
            from acg_tpu import commbench
            doc = commbench.collect_document(args, dtype, vec_dtype,
                                             sys.stderr)
            # the document is always emitted (stdout when FILE is
            # omitted/'-' -- the explain verdict goes to stderr, so
            # stdout is free): an unsaveable live calibration would
            # force the user to re-run the whole sweep
            try:
                commbench.write_document(doc, args.commbench)
            except OSError as e:
                sys.stderr.write(f"acg-tpu: --commbench "
                                 f"{args.commbench}: {e}\n")
            args._calibration = doc
            args._calibration_source = "live --commbench run"
        if args.plan is not None:
            # the decision observatory's no-dispatch mode: print the
            # ranked candidate table (and write the plan document)
            # WITHOUT solving -- the planning twin of the roofline
            # verdict below
            from acg_tpu.planner import run_plan_explain
            return run_plan_explain(args, dtype=dtype,
                                    vec_dtype=vec_dtype)
        from acg_tpu.perfmodel import run_explain
        return run_explain(args, dtype=dtype, vec_dtype=vec_dtype)

    def stage_sync(stage: str, code: int = 0) -> int:
        return _stage_sync(args, stage, code)

    if args.verbose >= 2:
        # part -> device mapping dump (the reference's rank -> CPU/GPU
        # map, cuda/acg-cuda.c:1055-1101)
        for d in jax.devices():
            _log(args, f"device {d.id}: {d.platform} {d.device_kind} "
                       f"(process {d.process_index})")

    if args.distributed_read:
        return _solve_distributed_read(args, jax, jnp, dtype, vec_dtype)

    # stages 1-4 under the ingest error-agreement guard: these are
    # the host-local stages (file I/O, partitioning) where one
    # controller can fail alone; the stage-sync below is the last
    # point before the first collective
    ingest_rc = 0
    t_ingest = time.perf_counter()
    try:
        # stage 1: read (or synthesize) the matrix
        t0 = time.perf_counter()
        if args.A.startswith("gen:"):
            spec = _parse_gen_spec(args.A)
            kind, dim, n, N = spec[:4]
            if (kind == "poisson" and N > _gen_direct_min()
                    and args.aniso is None):
                # too large for host CSR assembly: direct on-device DIA
                # (the aniso family keeps the host route: its graded
                # weights are not the pure-stencil device assembly)
                return _solve_generated_direct(args, dim, n, N, jax, jnp, dtype,
                                               vec_dtype)
            _log(args, f"synthesizing {args.A} (N={N})")
            A = synthesize_host_matrix(args.A, aniso=args.aniso,
                                       seed=args.seed)
            _log(args, "synthesize matrix:", t0)
        else:
            _log(args, f"reading matrix from {args.A}")
            try:
                mtx = read_mtx(args.A, binary=args.binary)
            except AcgError as e:
                raise SystemExit(f"acg-tpu: {args.A}: {e}")
            _log(args, "read matrix:", t0)
            A = SymCsrMatrix.from_mtx(mtx)

        # stage 2a: assemble symmetric CSR
        t0 = time.perf_counter()
        csr = A.to_csr(epsilon=args.epsilon)
        _log(args, "assemble symmetric CSR:", t0)
        args._phases.add("ingest", time.perf_counter() - t_ingest)

        n = A.nrows
        # partition-permuted input (mtx2bin --partition): the matrix on
        # disk is P A P^T, but user-facing vectors (b, x0, the printed
        # solution) stay in the ORIGINAL row ordering on every path
        perm_sidecar = (None if args.A.startswith("gen:")
                        else _load_perm_sidecar(args.A, n))

        # stage 2b/2c: partition rows and build subdomains
        nparts = args.nparts
        if comm == "none":
            nparts = nparts or 1
        else:
            nparts = nparts or len(jax.devices())
        t0 = time.perf_counter()
        if args.partition:
            try:
                pmtx = read_mtx(args.partition, binary=args.partition_binary)
            except AcgError as e:
                raise SystemExit(f"acg-tpu: {args.partition}: {e}")
            part = np.asarray(pmtx.vals, dtype=np.int64).reshape(-1)
            if part.size != n:
                raise SystemExit(f"acg-tpu: partition vector has {part.size} "
                                 f"entries, matrix has {n} rows")
            if part.min() == 1 and part.max() == nparts:
                part = part - 1  # tolerate 1-based partition vectors
            part = part.astype(np.int32)
            if part.max() >= nparts:
                nparts = int(part.max()) + 1
        else:
            method = args.partition_method
            if method == "auto":
                # banded matrices keep gather-free DIA local blocks under a
                # contiguous partition; everything else gets edge-cut
                # minimisation.  The O(nnz) probe only matters (and only
                # runs) when there is something to partition.
                if nparts > 1:
                    from acg_tpu.ops.spmv import prefers_dia
                    method = "band" if prefers_dia(csr) else "graph"
                else:
                    method = "graph"
            part = partition_rows(csr, nparts, seed=args.seed, method=method)
        _log(args, f"partition rows into {nparts} parts:", t0)
        args._phases.add("partition", time.perf_counter() - t0)

        # stage 4: right-hand side and initial guess
        rng = np.random.default_rng(args.seed)
        xsol = None
        if args._batched:
            # batched multi-RHS block (one column per system): b may
            # be an n x B dense array file (io.mtxfile.vector_columns),
            # a manufactured block, or B seeded random unit columns
            from acg_tpu.io.generators import batched_rhs
            from acg_tpu.io.mtxfile import vector_columns
            if args.manufactured_solution:
                xsol = rng.standard_normal((n, args.nrhs))
                xsol /= np.linalg.norm(xsol, axis=0, keepdims=True)
                b = np.column_stack(
                    [A.dsymv(xsol[:, j], epsilon=args.epsilon)
                     for j in range(args.nrhs)])
            elif args.b:
                bmtx = read_mtx(args.b, binary=args.binary)
                b = vector_columns(bmtx, n, args.nrhs)
                if perm_sidecar is not None:
                    b = b[perm_sidecar]
            else:
                b = batched_rhs(n, args.nrhs, seed=args.seed)
            if args.x0:
                xmtx = read_mtx(args.x0, binary=args.binary)
                x0 = vector_columns(xmtx, n, args.nrhs)
                if perm_sidecar is not None:
                    x0 = x0[perm_sidecar]
            else:
                x0 = None
        elif args.manufactured_solution:
            # random unit-norm solution; b = A*xsol via the independent host
            # SpMV (cuda/acg-cuda.c:1969-2140)
            xsol = rng.standard_normal(n)
            xsol /= np.linalg.norm(xsol)
            b = A.dsymv(xsol, epsilon=args.epsilon)
        elif args.b:
            bmtx = read_mtx(args.b, binary=args.binary)
            b = np.asarray(bmtx.vals, dtype=np.float64).reshape(-1)
            if b.size != n:
                raise SystemExit(f"acg-tpu: b has {b.size} entries, need {n}")
            if perm_sidecar is not None:
                b = b[perm_sidecar]
        else:
            b = np.ones(n)
        if args._batched:
            pass
        elif args.x0:
            xmtx = read_mtx(args.x0, binary=args.binary)
            x0 = np.asarray(xmtx.vals, dtype=np.float64).reshape(-1)
            if x0.size != n:
                # fail like the b path above does -- folding the size
                # check into the permute guard let a wrong-sized x0
                # proceed unpermuted (round-4 advisor finding)
                raise SystemExit(
                    f"acg-tpu: x0 has {x0.size} entries, need {n}")
            if perm_sidecar is not None:
                x0 = x0[perm_sidecar]
        else:
            x0 = None

        criteria = StoppingCriteria(
            maxits=args.max_iterations,
            residual_atol=args.residual_atol, residual_rtol=args.residual_rtol,
            diff_atol=args.diff_atol, diff_rtol=args.diff_rtol)
    except SystemExit as e:
        if e.code and not isinstance(e.code, int):
            sys.stderr.write(str(e.code) + "\n")
        ingest_rc = e.code if isinstance(e.code, int) else 1
    except (AcgError, OSError) as e:
        sys.stderr.write(f"acg-tpu: {e}\n")
        ingest_rc = 1
    rc = stage_sync("ingest", ingest_rc)
    if rc:
        if not ingest_rc:
            sys.stderr.write("acg-tpu: aborting: a peer controller "
                             "failed during ingest\n")
        return rc

    # decision observatory (acg_tpu.planner): plan the candidate
    # space, probe the top plans, and MUTATE the flag set the normal
    # construction flow below reads -- the planner only ever chooses
    # flags before solver construction, never alters program emission
    # (disarmed runs stay byte-identical, pinned in test_hlo_structure)
    if args.autotune:
        _run_autotune(args, csr, part, nparts, b, dtype, vec_dtype)
        # the winning candidate may have switched the halo transport
        comm = {"mpi": "xla", "nccl": "xla",
                "nvshmem": "dma"}.get(args.comm, args.comm)

    # stages 6b-8: build solver and solve, under the profiler when
    # --trace is set (try/finally so failed solves still finalise the
    # trace -- that is when it is most needed)
    t0 = time.perf_counter()
    pipelined = "pipelined" in args.solver
    if args.replace_every and args.solver in ("host", "host-native",
                                              "petsc"):
        sys.stderr.write("acg-tpu: --replace-every applies to the "
                         "device bf16 solvers (use --refine for "
                         "f64-grade accuracy on host paths)\n")
        stage_sync("solve", 1)
        return 1
    if args.replace_every and (args.diff_atol > 0 or args.diff_rtol > 0):
        sys.stderr.write("acg-tpu: --replace-every supports residual "
                         "criteria only (--diff-atol/--diff-rtol have "
                         "no meaning across replacement segments)\n")
        stage_sync("solve", 1)
        return 1
    comm_mtx_out = None
    from acg_tpu.tracing import profiler_trace
    with profiler_trace(args.trace):
        try:
            if args.solver == "host-native":
                from acg_tpu.solvers.host_cg import NativeHostCGSolver
                try:
                    solver = NativeHostCGSolver(csr)
                except RuntimeError as e:
                    sys.stderr.write(f"acg-tpu: {e}\n")
                    return 1
                x = _run_solve(args, solver, b, x0=x0, criteria=criteria)
            elif args.solver == "host":
                if nparts > 1 and comm != "none":
                    # the acgsolver_solvempi analog (cg.c:408): same
                    # partitioned layout as the device path, pure host
                    from acg_tpu import faults
                    from acg_tpu.errors import ErrorCode
                    from acg_tpu.graph import partition_matrix as _pm
                    from acg_tpu.solvers.host_cg import HostDistCGSolver
                    if faults.device_fault() is not None:
                        # the distributed host oracle has no injection
                        # sites either: refuse (replace_every rationale)
                        raise AcgError(
                            ErrorCode.INVALID_VALUE,
                            "fault injection has no injection sites in the "
                            "multi-part host solver; use the serial host "
                            "solver (--nparts 1) or the device solvers")
                    if args._precond is not None:
                        # silently running UNpreconditioned CG would not be
                        # the solve the user asked for (the fault-injector
                        # could-never-fire discipline): refuse
                        raise AcgError(
                            ErrorCode.INVALID_VALUE,
                            "--precond has no hooks in the multi-part host "
                            "solver; use --nparts 1 or the device solvers")
                    if args._health is not None:
                        # an armed audit that could never run (same rule)
                        raise AcgError(
                            ErrorCode.INVALID_VALUE,
                            "--audit-every/--stall-window have no hooks in "
                            "the multi-part host solver; use --nparts 1 or "
                            "the device solvers")
                    if args._ckpt is not None:
                        # armed snapshots that would never be written
                        raise AcgError(
                            ErrorCode.INVALID_VALUE,
                            "--ckpt/--resume have no hooks in the "
                            "multi-part host solver; use --nparts 1 or "
                            "the device solvers")
                    if args._recovery is not None:
                        sys.stderr.write(
                            "acg-tpu: warning: --recover has no effect on "
                            "the multi-part host solver (no breakdown "
                            "detection there)\n")
                    if args._trace or args.progress:
                        sys.stderr.write(
                            "acg-tpu: warning: --convergence-log/--progress "
                            "have no hooks in the multi-part host solver; "
                            "use --nparts 1 or the device solvers\n")
                    solver = HostDistCGSolver(_pm(csr, part, nparts))
                else:
                    solver = HostCGSolver(csr, recovery=args._recovery,
                                          trace=args._trace,
                                          progress=args.progress,
                                          precond=args._precond,
                                          health=args._health,
                                          ckpt=args._ckpt)
                x = _run_solve(args, solver, b, x0=x0, criteria=criteria)
            elif args.solver == "petsc":
                # external cross-implementation oracle (the KSPCG role,
                # cgpetsc.c:181) backed by scipy.sparse.linalg.cg
                from acg_tpu.solvers.petsc_cg import PetscBaselineSolver
                solver = PetscBaselineSolver(csr, pipelined=pipelined)
                x = _run_solve(args, solver, b, x0=x0, criteria=criteria)
            elif args._batched:
                # batched multi-RHS tier: B columns, ONE solve (the
                # solvers.batched / parallel.dist_batched programs)
                mode = ("block" if args.block_cg
                        else "pipelined" if pipelined else "batched")
                if comm == "none" or nparts == 1:
                    from acg_tpu.solvers.batched import BatchedCGSolver
                    if args._operator_spec is not None:
                        # matrix-free batched: spmv_multi dispatches on
                        # the operator's multi-column apply
                        dev = _build_cli_operator(args, n, dtype)
                    else:
                        dev = device_matrix_from_csr(
                            csr, dtype=dtype, format=args.spmv_format)
                    try:
                        solver = BatchedCGSolver(
                            dev, mode=mode,
                            precise_dots=args.precise_dots,
                            vector_dtype=vec_dtype,
                            precond=args._precond, trace=args._trace,
                            ckpt=args._ckpt, host_matrix=csr)
                    except ValueError as e:
                        raise SystemExit(f"acg-tpu: {e}")
                else:
                    if args.block_cg:
                        raise SystemExit(
                            "acg-tpu: --block-cg is a single-device "
                            "tier (its B x B Gram solves are not "
                            "distributed); use --nparts 1/--comm none, "
                            "or drop --block-cg for the batched mesh "
                            "tier")
                    from acg_tpu.parallel.dist_batched import \
                        BatchedDistCGSolver
                    from acg_tpu.parallel.mesh import solve_mesh
                    mesh = solve_mesh(nparts)
                    subs = partition_matrix(csr, part, nparts)
                    prob = DistributedProblem.build(
                        csr, part, nparts, dtype=dtype, subs=subs,
                        vector_dtype=vec_dtype)
                    try:
                        solver = BatchedDistCGSolver(
                            prob, pipelined=pipelined, mesh=mesh,
                            precise_dots=args.precise_dots,
                            precond=args._precond, trace=args._trace,
                            ckpt=args._ckpt)
                    except ValueError as e:
                        raise SystemExit(f"acg-tpu: {e}")
                x = _run_solve(args, solver, b, x0=x0,
                               criteria=criteria, warmup=args.warmup)
            elif comm == "none" or nparts == 1:
                if args._operator_spec is not None:
                    # matrix-free: the operator IS the device matrix
                    # (ops.spmv dispatches on the matfree protocol)
                    dev = _build_cli_operator(args, n, dtype)
                else:
                    dev = device_matrix_from_csr(csr, dtype=dtype,
                                                 format=args.spmv_format)
                try:
                    solver = JaxCGSolver(dev, pipelined=pipelined,
                                         precise_dots=args.precise_dots,
                                         kernels=args.kernels,
                                         vector_dtype=vec_dtype,
                                         replace_every=args.replace_every,
                                         recovery=args._recovery,
                                         host_matrix=csr,
                                         trace=args._trace,
                                         progress=args.progress,
                                         precond=args._precond,
                                         health=args._health,
                                         ckpt=args._ckpt,
                                         algorithm=args._algorithm)
                except ValueError as e:
                    raise SystemExit(f"acg-tpu: {e}")
                if args.refine:
                    solver = RefinedSolver(solver, csr,
                                           inner_rtol=args.refine_rtol)
                x = _run_solve(args, solver, b, x0=x0, criteria=criteria,
                               warmup=args.warmup)
            else:
                from acg_tpu.parallel.mesh import solve_mesh
                mesh = solve_mesh(nparts)
                # multi-controller: each process assembles matrix blocks and
                # host arrays ONLY for the parts its mesh devices own --
                # per-controller preprocessing memory is O(N/P), the role of
                # the reference's root-read + subgraph scatter
                # (graph.c:1529-1897) without the scatter
                owned = None
                if jax.process_count() > 1:
                    pi = jax.process_index()
                    owned = tuple(p for p in range(nparts)
                                  if mesh.devices.flat[p].process_index == pi)
                subs = partition_matrix(csr, part, nparts, owned_parts=owned)
                if args.output_comm_matrix:
                    comm_mtx_out = comm_matrix(subs, nparts)
                prob = DistributedProblem.build(csr, part, nparts, dtype=dtype,
                                                subs=subs,
                                                vector_dtype=vec_dtype,
                                                owned_parts=owned)
                if args._operator_spec is not None:
                    # matrix-free on the mesh: generated local planes
                    # behind the SAME halo plan and ghost block
                    from acg_tpu.parallel.dist import arm_matfree
                    arm_matfree(prob, _build_cli_operator(args, n,
                                                          dtype))
                try:
                    solver = DistCGSolver(prob, pipelined=pipelined, comm=comm,
                                          precise_dots=args.precise_dots,
                                          kernels=args.kernels, mesh=mesh,
                                          replace_every=args.replace_every,
                                          recovery=args._recovery,
                                          trace=args._trace,
                                          progress=args.progress,
                                          precond=args._precond,
                                          health=args._health,
                                          ckpt=args._ckpt,
                                          algorithm=args._algorithm)
                except ValueError as e:
                    raise SystemExit(f"acg-tpu: {e}")
                if args.refine:
                    solver = RefinedSolver(solver, csr,
                                           inner_rtol=args.refine_rtol)
                x = _run_solve(args, solver, b, x0=x0, criteria=criteria,
                               warmup=args.warmup)
        except (NotConvergedError, BreakdownError) as e:
            sys.stderr.write(f"acg-tpu: {e}\n")
            # plan-vs-actual still records: a planned program that
            # failed to converge is the strongest correction signal
            _finalize_plan(args, solver)
            _fold_phases(args, solver)
            if is_primary():  # stats block from "rank 0" only
                solver.stats.fwrite(sys.stderr)
            # the convergence log is most needed exactly when the solve
            # failed: the trailing window shows the trajectory into the
            # divergence/breakdown (no collective gather on this path)
            _emit_telemetry(args, solver, matrix_id=args.A, nparts=nparts,
                            comm=comm, collective=False)
            stage_sync("solve", 1)
            return 1
        except AcgError as e:
            sys.stderr.write(f"acg-tpu: {e}\n")
            stage_sync("solve", 1)
            return 1
    _log(args, "solve:", t0)
    # plan-vs-actual BEFORE the stats block renders: the 'plan:'
    # section and its misprediction ratio ride fwrite, --stats-json
    # and the history ledger (where later planned runs consult them)
    _finalize_plan(args, solver)
    rc = stage_sync("solve", 0)
    if rc:
        sys.stderr.write("acg-tpu: aborting: a peer controller failed "
                         "during the solve\n")
        return rc

    # optional per-op timing tier (replayed, see solvers/profile.py);
    # None = flag absent, any given value is clamped to >= 1 rep
    if args.profile_ops is not None:
        from acg_tpu.solvers.profile import profile_ops
        per_call = profile_ops(solver, b, reps=max(args.profile_ops, 1))
        _report_chain_overhead(per_call)
    # AFTER the replay tier: where the capture measured an op class,
    # the measured seconds supersede the replay estimate
    _attach_trace_analysis(args, solver)

    # every controller solves; only "rank 0" speaks (the reference's
    # fwritempi / mtxfile_fwrite_mpi_double root-rank output convention)
    # -- but the telemetry rank gather is COLLECTIVE, so non-primary
    # controllers contribute their payload before returning
    _fold_phases(args, solver)
    if not is_primary():
        _emit_telemetry(args, solver, matrix_id=args.A, nparts=nparts,
                        comm=comm)
        return 0

    # stage 9: statistics block (grep-compatible with the reference)
    solver.stats.fwrite(sys.stderr)

    # stage 9b: manufactured-solution error norms (batched: Frobenius
    # over the column block, plus the worst single column)
    if xsol is not None:
        x0ref = x0 if x0 is not None else np.zeros_like(xsol)
        err0 = np.linalg.norm(x0ref - xsol)
        err = np.linalg.norm(np.asarray(x) - xsol)
        sys.stderr.write(f"initial error 2-norm: {err0:.15g}\n")
        sys.stderr.write(f"error 2-norm: {err:.15g}\n")
        if xsol.ndim == 2 and xsol.shape[1] > 1:
            per = np.linalg.norm(np.asarray(x) - xsol, axis=0)
            sys.stderr.write(f"worst per-RHS error 2-norm: "
                             f"{float(per.max()):.15g} "
                             f"(rhs {int(per.argmax())})\n")

    # stage 2d/10: communication matrix and solution output
    if comm_mtx_out is not None:
        _write_comm_matrix(comm_mtx_out, nparts)
    t_wb = time.perf_counter()
    _emit_solution(args, x, perm_sidecar)
    args._phases.add("writeback", time.perf_counter() - t_wb)
    # the structured sink is written LAST so it includes the writeback
    # phase (the text block above, printed before output, cannot)
    _emit_telemetry(args, solver, matrix_id=args.A, nparts=nparts,
                    comm=comm)
    return 0


if __name__ == "__main__":
    sys.exit(main())
