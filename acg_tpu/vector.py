"""Dense vectors with trailing ghost entries (host-side, numpy).

Rebuilds the reference's ``acg/vector.c`` (SURVEY.md component #9): a dense
vector whose last ``num_ghost`` entries mirror remote data and are excluded
from reductions (``vector.h:152-160``), BLAS-1 operations with analytic
flop/byte accounting, and the sparse gather (``usga``) used to extract
partition-conforming subvectors.  MPI send/recv/scatter variants collapse
into plain slicing here because the TPU build is single-controller.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class PVector:
    """A vector of ``size`` entries of which the trailing ``num_ghost`` are
    ghost copies of remote entries (excluded from dot products and norms)."""

    data: np.ndarray
    num_ghost: int = 0

    @classmethod
    def zeros(cls, n: int, num_ghost: int = 0, dtype=np.float64) -> "PVector":
        return cls(np.zeros(n + num_ghost, dtype=dtype), num_ghost)

    @property
    def size(self) -> int:
        return self.data.size

    @property
    def num_owned(self) -> int:
        return self.data.size - self.num_ghost

    @property
    def owned(self) -> np.ndarray:
        """View of the non-ghost entries (reductions operate on this)."""
        return self.data[: self.num_owned]

    # BLAS-1, ghost-aware (cf. vector.h:335-415).  Updates write through
    # the owned view with explicit ``out=`` (augmented assignment on the
    # ``owned`` property would try to rebind it).
    def dot(self, other: "PVector") -> float:
        return float(np.dot(self.owned, other.owned))

    def nrm2(self) -> float:
        return float(np.linalg.norm(self.owned))

    def axpy(self, alpha: float, x: "PVector") -> None:
        owned = self.owned
        np.add(owned, alpha * x.owned, out=owned)

    def aypx(self, alpha: float, x: "PVector") -> None:
        """y = alpha*y + x (the reference's ``daypx``)."""
        owned = self.owned
        np.multiply(owned, alpha, out=owned)
        np.add(owned, x.owned, out=owned)

    def scal(self, alpha: float) -> None:
        owned = self.owned
        np.multiply(owned, alpha, out=owned)

    def copy_from(self, x: "PVector") -> None:
        np.copyto(self.data, x.data)

    def gather(self, idx: np.ndarray) -> np.ndarray:
        """Sparse gather of entries at ``idx`` (the reference's ``usga``)."""
        return self.data[idx]

    def scatter_into(self, idx: np.ndarray, values: np.ndarray) -> None:
        """Sparse scatter (the reference's ``ussc``); used to unpack halos."""
        self.data[idx] = values
