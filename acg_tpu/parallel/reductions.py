"""Fused mesh reductions shared by every distributed recurrence.

The communication-avoiding property of the pipelined/batched/s-step
tiers is carried by ONE idiom: stack k locally-computed scalars (or
B-wide scalar columns), psum the stack once, unpack.  Before this
module the idiom was hand-copied as ``pdot2_fused``/``pdot3_fused``
(parallel/dist.py) and ``pdot2_fused_cols`` (parallel/dist_batched.py),
and every new recurrence re-derived it; now :func:`make_pdot` /
:func:`make_pdotk` / :func:`make_pdotk_cols` build the whole family
from the tier's own ``psum`` + local-dot, and the s-step Gram / p(l)
z-window reductions (``TierOps.psum_stack`` in acg_tpu.recurrence) are
the same idiom with a matrix payload.

Byte-compatibility contract: the builders emit EXACTLY the op sequence
the hand-written ladders traced (stack order, compensated hi/lo
interleave), so the refactored dist/dist_batched programs lower
byte-identically to the pre-refactor ones (the HLO pins in
tests/test_hlo_structure.py and tests/test_batched.py did not move).
"""

from __future__ import annotations

import jax.numpy as jnp

from acg_tpu.ops.precision import dot_compensated


def make_pdot(psum, ldot, sdt, precise: bool):
    """The single global dot product: ``pdot(a, c)`` = one psum of one
    scalar (plain) or of the compensated hi/lo pair (``precise``)."""
    if precise:
        def pdot(a, c):
            hi, lo = dot_compensated(a.astype(sdt), c.astype(sdt))
            pair = psum(jnp.stack([hi, lo]))
            return pair[0] + pair[1]
    else:
        def pdot(a, c):
            return psum(ldot(a, c))
    return pdot


def make_pdotk(psum, ldot, sdt, precise: bool):
    """``pdotk((a1, c1), ..., (ak, ck))`` -> k global scalars in ONE
    psum -- the fused-reduction ladder every communication-avoiding
    recurrence rides (classic PCG's (r,z)+(r,r) pair, the pipelined
    tier's 2- and 3-scalar fusions, the ABFT 3-dot, the s-step Gram's
    scalar tail).  Compensated mode interleaves hi/lo pairs exactly
    like the hand-written ``pdot2_fused``/``pdot3_fused`` did."""
    if precise:
        def pdotk(*pairs):
            hls = [dot_compensated(a.astype(sdt), c.astype(sdt))
                   for a, c in pairs]
            flat = psum(jnp.stack([v for hl in hls for v in hl]))
            return tuple(flat[2 * i] + flat[2 * i + 1]
                         for i in range(len(pairs)))
    else:
        def pdotk(*pairs):
            red = psum(jnp.stack([ldot(a, c) for a, c in pairs]))
            return tuple(red[i] for i in range(len(pairs)))
    return pdotk


def make_pdot_cols(psum, lcoldot, sdt, precise: bool):
    """The single B-column global dot (batched tier): one psum of a
    (B,) column (plain) or of the stacked compensated hi/lo columns."""
    if precise:
        import jax

        def pdot_cols(a, c):
            def one(u, v):
                return dot_compensated(u.astype(sdt), v.astype(sdt))
            hi, lo = jax.vmap(one, in_axes=1)(a, c)
            pair = psum(jnp.stack([hi, lo]))
            return pair[0] + pair[1]
    else:
        def pdot_cols(a, c):
            return psum(lcoldot(a, c))
    return pdot_cols


def make_pdotk_cols(psum, lcoldot, sdt, precise: bool):
    """The B-column twin of :func:`make_pdotk` (the batched tier):
    ``pdotk_cols((A1, C1), ..., (Ak, Ck))`` -> k length-B scalar
    columns in ONE psum of a (k, B) (or (2k, B) compensated) stack --
    the mesh collective count stays invariant in B."""
    if precise:
        import jax

        def _comp_cols(a, c):
            def one(u, v):
                return dot_compensated(u.astype(sdt), v.astype(sdt))
            hi, lo = jax.vmap(one, in_axes=1)(a, c)
            return hi, lo

        def pdotk_cols(*pairs):
            hls = [_comp_cols(a, c) for a, c in pairs]
            flat = psum(jnp.stack([v for hl in hls for v in hl]))
            return tuple(flat[2 * i] + flat[2 * i + 1]
                         for i in range(len(pairs)))
    else:
        def pdotk_cols(*pairs):
            red = psum(jnp.stack([lcoldot(a, c) for a, c in pairs]))
            return tuple(red[i] for i in range(len(pairs)))
    return pdotk_cols
