"""Batched multi-RHS CG over the device mesh: B systems, ONE solve.

The distributed twin of :mod:`acg_tpu.solvers.batched` -- the classic
and pipelined SPMD recurrences of :mod:`acg_tpu.parallel.dist` with a
trailing batch axis.  The communication contract is the tentpole:

* the halo exchange moves ``(maxcnt, B)`` windows through the SAME
  single ``all_to_all`` per iteration (payload grows with B, the
  collective count does not);
* ALL per-RHS dot products fuse into B-WIDE allreduces -- classic CG
  keeps its 2 psums per iteration (now of (B,) vectors), pipelined CG
  keeps its SINGLE fused psum (now 2B scalars; the
  ``pdot2_fused``/``pdot3_fused`` column variants).  On a multi-hop
  ICI mesh B allreduces of 1 scalar cost ~B x the latency of 1
  allreduce of B scalars (arXiv 1905.06850's global-reduction
  argument), so the collective count staying INVARIANT IN B is the
  whole point -- pinned at the HLO level in tests/test_batched.py.

Per-RHS convergence masks ride the carry exactly as on the
single-device tier; every masked scalar is psum'd, so the masks are
mesh-uniform and the loop runs to the slowest unconverged RHS on every
shard alike.  A single-column batch delegates to the plain
:class:`~acg_tpu.parallel.dist.DistCGSolver` -- B=1 lowers
byte-identical HLO (the disarmed-identity discipline)."""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from acg_tpu._platform import shard_map as _shard_map
from acg_tpu.errors import AcgError, ErrorCode, NotConvergedError
from acg_tpu.ops.spmv import acc_dtype
from acg_tpu.parallel.dist import DistributedProblem
from acg_tpu.parallel.mesh import PARTS_AXIS, solve_mesh
from acg_tpu.parallel.reductions import make_pdot_cols, make_pdotk_cols
from acg_tpu.parallel.multihost import get_global, put_global
from acg_tpu.solvers.stats import (SolverStats, StoppingCriteria,
                                   cg_flops_per_iteration)

__all__ = ["BatchedDistCGSolver"]


def _local_mv_multi(block, arrays, X):
    """``Y = A_local @ X`` for one shard's local block, multi-column
    ``X`` (nrows, B) -- one pass over the block for all columns."""
    adt = acc_dtype(X.dtype)
    if block.format == "dia":
        # dia_mv generalises column-wise via a vmap over the batch
        # axis of the statically-sliced views; the planes are read
        # once per slice either way (XLA hoists the broadcast)
        L = max(0, -min(block.offsets))
        R = max(0, max(block.offsets) + block.nrows - X.shape[0])
        Xp = jnp.pad(X, ((L, R), (0, 0)))
        Y = jnp.zeros((block.nrows, X.shape[1]), dtype=adt)
        for plane, off in zip(arrays, block.offsets):
            sl = lax.dynamic_slice_in_dim(Xp, L + off, block.nrows, 0)
            Y = Y + plane[:, None].astype(adt) * sl.astype(adt)
        return Y.astype(X.dtype)
    if block.format == "binnedell":
        bin_rows, bin_data, bin_cols, t_rows, t_cols, t_vals = arrays
        Y = jnp.zeros((block.nrows, X.shape[1]), dtype=adt)
        for rows, data, cols in zip(bin_rows, bin_data, bin_cols):
            contrib = jnp.einsum("mk,mkb->mb", data, X[cols],
                                 preferred_element_type=adt)
            Y = Y.at[rows].add(contrib)
        if t_vals.shape[-1]:
            prod = t_vals[:, None].astype(adt) * X[t_cols].astype(adt)
            Y = Y.at[t_rows].add(prod)
        return Y.astype(X.dtype)
    data, cols = arrays
    return jnp.einsum("nk,nkb->nb", data, X[cols],
                      preferred_element_type=adt).astype(X.dtype)


def _ghost_mv_multi(block, arrays, Xg):
    rows, data, cols = arrays
    contrib = jnp.einsum("bk,bkc->bc", data, Xg[cols],
                         preferred_element_type=acc_dtype(Xg.dtype)
                         ).astype(Xg.dtype)
    return jnp.zeros((block.nrows, Xg.shape[1]), Xg.dtype).at[rows].add(
        contrib, indices_are_sorted=True)


def _squeeze_col(x0):
    """A single-column (n, 1) x0 -> the (n,) vector the delegated
    single-RHS solver's scatter consumes (B=1 delegation)."""
    if x0 is None:
        return None
    x0 = np.asarray(x0)
    return x0[:, 0] if x0.ndim == 2 else x0


def _halo_exchange_multi(X_loc, send_idx, ghost_src,
                         axis: str = PARTS_AXIS):
    """Multi-column halo exchange: the SAME single all_to_all as the
    single-RHS transport, its payload widened by the batch axis."""
    with jax.named_scope("halo_exchange_multi"):
        sendbuf = X_loc[send_idx]           # (nparts, maxcnt, B)
        recvbuf = lax.all_to_all(sendbuf, axis, split_axis=0,
                                 concat_axis=0, tiled=True)
        return recvbuf.reshape(-1, X_loc.shape[1])[ghost_src]


class BatchedDistCGSolver:
    """Whole-solve batched SPMD CG over a 1-D mesh: B right-hand-side
    columns against one partitioned operator, collective count
    invariant in B.

    Supports the classic (2 B-wide psums/iteration) and pipelined
    (1 fused 2B-scalar psum) recurrences, per-RHS convergence masks,
    the per-RHS telemetry ring, and -- classic mode -- checkpointed
    chunked solves whose per-part per-RHS carry leaves survive
    preemption and ``--resume-repartition`` onto a different mesh."""

    _ckpt_tier = "dist-cg-batched"

    def __init__(self, problem: DistributedProblem,
                 pipelined: bool = False, mesh=None,
                 precise_dots: bool = False, precond=None,
                 trace: int = 0, ckpt=None):
        if precond is not None:
            from acg_tpu.precond import parse_precond
            if parse_precond(precond) is not None:
                raise ValueError(
                    "the batched distributed tier runs unpreconditioned "
                    "CG (preconditioned batching lives on the "
                    "single-device tier, acg_tpu.solvers.batched); "
                    "drop precond or use nparts=1")
        if problem.local.format == "matfree":
            raise ValueError(
                "the batched distributed tier runs assembled local "
                "blocks (its multi-vector shard SpMV has no generated-"
                "plane form yet); matrix-free batching lives on the "
                "single-device tier (acg_tpu.solvers.batched), or drop "
                "--nrhs for the matrix-free mesh solve")
        self.problem = problem
        self.pipelined = bool(pipelined)
        self.precise_dots = bool(precise_dots)
        self.mesh = mesh if mesh is not None else solve_mesh(problem.nparts)
        self.stats = SolverStats(unknowns=problem.n)
        self._sharding = NamedSharding(self.mesh, P(PARTS_AXIS))
        self.trace = int(trace)
        if self.trace < 0:
            raise ValueError("trace must be >= 0")
        if ckpt is not None:
            from acg_tpu.checkpoint import CheckpointConfig
            if not isinstance(ckpt, CheckpointConfig):
                raise ValueError("ckpt must be an acg_tpu.checkpoint."
                                 "CheckpointConfig or None")
            if self.pipelined:
                raise ValueError(
                    "batched checkpointing threads the batched-classic "
                    "carry; the pipelined batched mode does not expose "
                    "state_io")
        self.ckpt = ckpt
        self.last_trace = None
        self._inner1 = None
        self._programs: dict = {}

    # -- B=1 delegation ----------------------------------------------------

    def _inner(self):
        if self._inner1 is None:
            from acg_tpu.parallel.dist import DistCGSolver
            self._inner1 = DistCGSolver(
                self.problem, pipelined=self.pipelined, mesh=self.mesh,
                precise_dots=self.precise_dots, trace=self.trace,
                ckpt=self.ckpt)
        return self._inner1

    # -- program construction ---------------------------------------------

    def _program_for(self, nrhs: int, state_io: bool = False):
        key = (int(nrhs), bool(state_io))
        prog = self._programs.get(key)
        if prog is None:
            prog = self._programs[key] = self._compile(nrhs, state_io)
        return prog

    def _compile(self, nrhs: int, state_io: bool):
        prob = self.problem
        pipelined = self.pipelined
        axis = PARTS_AXIS
        precise = self.precise_dots
        trace = self.trace
        halo = prob.halo
        local_block = prob.local
        ghost_block = prob.ghost
        single_shard = self.mesh.devices.size == 1
        if trace:
            from acg_tpu import telemetry

        def psum(v):
            return v if single_shard else lax.psum(v, axis)

        def shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                       atols, rtol, maxits, unbounded=False,
                       carry=None):
            la, ga = (jax.tree.map(lambda a: a[0], t) for t in (la, ga))
            sidx, gsrc, gval, scnt, rcnt, b, x0 = (
                a[0] for a in (sidx, gsrc, gval, scnt, rcnt, b, x0))
            if carry is not None:
                # vector leaves arrive stacked (1, pad, B); the per-RHS
                # column vectors (B,) arrive replicated
                carry = tuple(a[0] if a.ndim == 3 else a for a in carry)
            maxits = maxits.astype(jnp.int32)
            dtype = b.dtype
            sdt = acc_dtype(dtype)
            store = ((lambda v: v.astype(dtype)) if sdt != dtype
                     else (lambda v: v))
            # atols may be a scalar (first dispatch) or the chunk
            # driver's per-RHS absolute-target vector (resume keeps
            # every column's ORIGINAL tolerance)
            res_atol, res_rtol = atols, rtol

            def spmv(X):
                y = _local_mv_multi(local_block, la, X)
                if halo.has_ghosts:
                    ghost = _halo_exchange_multi(X, sidx, gsrc, axis)
                    y = y + _ghost_mv_multi(ghost_block, ga, ghost)
                return y

            def lcoldot(a, c):
                return jnp.einsum("nb,nb->b", a, c,
                                  preferred_element_type=sdt)

            # the fused-reduction family (parallel.reductions), B-wide:
            # ONE psum carries k B-column payloads (the mesh collective
            # count stays invariant in B; compensated mode interleaves
            # hi/lo column pairs) -- byte-identical emission to the
            # hand-written ladders this replaced (tests/test_batched.py
            # pins the counts)
            pdot_cols = make_pdot_cols(psum, lcoldot, sdt, precise)
            _pdotk_cols = make_pdotk_cols(psum, lcoldot, sdt, precise)

            def pdot2_fused_cols(a1, c1, a2, c2):
                return _pdotk_cols((a1, c1), (a2, c2))

            bnrm2 = jnp.sqrt(pdot_cols(b, b))
            x0nrm2 = jnp.sqrt(pdot_cols(x0, x0))
            inf = jnp.full((nrhs,), jnp.inf, sdt)
            if carry is not None:
                r = carry[0]
                gamma = carry[2]
                done0, iters0 = (carry[3].astype(bool),
                                 carry[4].astype(jnp.int32))
                r0nrm2 = jnp.sqrt(gamma)
            else:
                r = b - spmv(x0)
                gamma = pdot_cols(r, r)
                r0nrm2 = jnp.sqrt(gamma)
                done0 = iters0 = None
            res_tol = jnp.maximum(res_atol, res_rtol * r0nrm2)

            def active_div(num, den, active):
                ok = active & (den != 0)
                return jnp.where(ok, num / jnp.where(den != 0, den, 1.0),
                                 jnp.zeros_like(num))

            def colw(mask, new, old):
                return jnp.where(mask[None, :], new, old)

            if not pipelined:
                def body(k, st):
                    if trace:
                        buf, st = st[-1], st[:-1]
                    X, R, Pv, gamma, done, iters = st
                    active = ~done
                    T = spmv(Pv)
                    pdott = pdot_cols(Pv, T)         # psum 1: (B,)
                    alpha = active_div(gamma, pdott, active)
                    X = colw(active, store(X + alpha[None, :] * Pv), X)
                    R = colw(active, store(R - alpha[None, :] * T), R)
                    gamma_next = pdot_cols(R, R)     # psum 2: (B,)
                    beta = active_div(gamma_next, gamma, active)
                    Pv = colw(active, store(R + beta[None, :] * Pv), Pv)
                    iters = iters + active.astype(jnp.int32)
                    gamma = jnp.where(active, gamma_next, gamma)
                    if not unbounded:
                        done = done | (active
                                       & (gamma_next
                                          < res_tol * res_tol))
                    out = (X, R, Pv, gamma, done, iters)
                    if trace:
                        out = out + (telemetry.ring_record_batched(
                            buf, k, gamma_next),)
                    return out

                if done0 is None:
                    done0 = (jnp.zeros((nrhs,), bool) if unbounded
                             else gamma < res_tol * res_tol)
                    iters0 = jnp.zeros((nrhs,), jnp.int32)
                if carry is not None:
                    init = (x0, carry[0], carry[1], gamma, done0,
                            iters0)
                else:
                    init = (x0, r, r, gamma, done0, iters0)
            else:
                w0 = spmv(r)
                zeros = jnp.zeros_like(b)

                def body(k, st):
                    if trace:
                        buf, st = st[-1], st[:-1]
                    (X, R, W, Pv, T, Z, gamma_prev, alpha_prev, done,
                     iters) = st
                    active = ~done
                    # the SINGLE fused B-wide allreduce per iteration
                    gamma, delta = pdot2_fused_cols(R, R, W, R)
                    Q = spmv(W)
                    beta = active_div(gamma, gamma_prev, active)
                    denom = delta - beta * active_div(gamma, alpha_prev,
                                                      active)
                    alpha = active_div(gamma, denom, active)
                    Z = colw(active, store(Q + beta[None, :] * Z), Z)
                    T = colw(active, store(W + beta[None, :] * T), T)
                    Pv = colw(active, store(R + beta[None, :] * Pv), Pv)
                    X = colw(active, store(X + alpha[None, :] * Pv), X)
                    R = colw(active, store(R - alpha[None, :] * T), R)
                    W = colw(active, store(W - alpha[None, :] * Z), W)
                    iters = iters + active.astype(jnp.int32)
                    if not unbounded:
                        done = done | (active
                                       & (gamma < res_tol * res_tol))
                    gamma_c = jnp.where(active, gamma, gamma_prev)
                    alpha_c = jnp.where(active, alpha, alpha_prev)
                    out = (X, R, W, Pv, T, Z, gamma_c, alpha_c, done,
                           iters)
                    if trace:
                        out = out + (telemetry.ring_record_batched(
                            buf, k, gamma),)
                    return out

                done0 = (jnp.zeros((nrhs,), bool) if unbounded
                         else gamma < res_tol * res_tol)
                iters0 = jnp.zeros((nrhs,), jnp.int32)
                init = (x0, r, w0, zeros, zeros, zeros, inf, inf,
                        done0, iters0)

            if trace:
                init = init + (telemetry.ring_init_batched(
                    trace, nrhs, sdt),)
            if unbounded:
                state = lax.fori_loop(0, maxits, body, init)
                k = maxits
            else:
                di = 4 if not pipelined else 8

                def cond(c):
                    k, st = c
                    return (k < maxits) & jnp.any(~st[di])

                def wbody(c):
                    k, st = c
                    return (k + 1, body(k, st))

                k, state = lax.while_loop(cond, wbody,
                                          (jnp.int32(0), init))
            tbuf = None
            if trace:
                tbuf, state = state[-1], state[:-1]
            if not pipelined:
                X, R, Pv, gamma, done, iters = state
                rnrm2 = jnp.sqrt(gamma)
            else:
                X, R = state[0], state[1]
                done, iters = state[8], state[9]
                rnrm2 = jnp.sqrt(pdot_cols(R, R))
                done = done | (rnrm2 <= res_tol)
            # unbounded: "converged" = ran the budget, but only in
            # the reported tuple -- the state_io carry keeps the
            # loop's own mask/totals so a later chunk is not frozen
            done_res = (jnp.ones((nrhs,), bool) if unbounded
                        else done)
            out = (X[None], iters, jnp.asarray(k, jnp.int32), rnrm2,
                   r0nrm2, bnrm2, x0nrm2, done_res)
            if trace:
                out = out + (tbuf,)
            if state_io and not pipelined:
                out = out + (R[None], Pv[None], gamma, done, iters)
            return out

        if single_shard and not prob.halo.has_ghosts:
            @functools.partial(jax.jit, static_argnames=("unbounded",))
            def program(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                        atols, rtol, maxits, unbounded, carry=None):
                return shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt,
                                  b, x0, atols, rtol, maxits,
                                  unbounded=unbounded, carry=carry)

            return program

        pspec = P(PARTS_AXIS)
        rspec = P()
        in_specs = (pspec, pspec, pspec, pspec, pspec, pspec, pspec,
                    pspec, pspec, rspec, rspec, rspec)
        out_specs = (pspec,) + (rspec,) * 7
        if trace:
            out_specs = out_specs + (rspec,)
        carry_specs = (pspec, pspec, rspec, rspec, rspec)
        if state_io:
            out_specs = out_specs + carry_specs

        @functools.partial(jax.jit, static_argnames=("unbounded",))
        def program(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                    atols, rtol, maxits, unbounded, carry=None):
            extra = ()
            specs = in_specs
            if carry is not None:
                extra = (tuple(carry),)
                specs = specs + (carry_specs,)

            def smb(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                    atols, rtol, maxits, *rest):
                cr = rest[0] if rest else None
                return shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt,
                                  b, x0, atols, rtol, maxits,
                                  unbounded=unbounded, carry=cr)

            return _shard_map(
                smb, mesh=self.mesh, in_specs=specs,
                out_specs=out_specs,
            )(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, atols,
              rtol, maxits, *extra)

        return program

    # -- placement ---------------------------------------------------------

    def _scatter_cols(self, Xg, dtype):
        """(n, B) global columns -> (nparts, nmax_owned, B) stacked."""
        prob = self.problem
        Xg = np.asarray(Xg)
        out = np.zeros((prob.nparts, prob.nmax_owned, Xg.shape[1]),
                       dtype=np.dtype(dtype))
        for j in range(Xg.shape[1]):
            out[:, :, j] = prob.scatter(Xg[:, j], dtype=dtype)
        return out

    def _gather_cols(self, stacked):
        prob = self.problem
        st = np.asarray(stacked)
        out = np.zeros((prob.n, st.shape[2]), dtype=st.dtype)
        for j in range(st.shape[2]):
            out[:, j] = prob.gather(st[:, :, j])
        return out

    def device_args(self, B_global, x0=None):
        prob = self.problem
        dtype = np.dtype(prob.vdtype)
        put = functools.partial(put_global, sharding=self._sharding)
        Bg = np.asarray(B_global)
        if Bg.ndim == 1:
            Bg = Bg[:, None]
        b = put(self._scatter_cols(Bg, dtype))
        x0_st = put(self._scatter_cols(np.asarray(x0), dtype)
                    if x0 is not None
                    else np.zeros((prob.nparts, prob.nmax_owned,
                                   Bg.shape[1]), dtype=dtype))
        la = jax.tree.map(put, prob.local.arrays)
        ga = jax.tree.map(put, (prob.ghost.rows, prob.ghost.data,
                                prob.ghost.cols))
        sidx = put(prob.halo.send_idx)
        gsrc = put(prob.halo.ghost_src)
        gval = put(prob.halo.ghost_valid)
        scnt_np, rcnt_np = prob.neighbor_counts()
        return (b, x0_st, la, ga, sidx, gsrc, gval,
                put(scnt_np), put(rcnt_np))

    def lower_solve(self, B_global, x0=None, criteria=None):
        """Lower (don't run) the dispatched program -- the HLO-pin
        hook asserting the collective count is invariant in B.  A
        single column delegates to the plain DistCGSolver (byte
        identity)."""
        Bg = np.asarray(B_global)
        if Bg.ndim == 1 or Bg.shape[1] == 1:
            return self._inner().lower_solve(
                Bg.reshape(Bg.shape[0]), x0=_squeeze_col(x0),
                criteria=criteria)
        crit = criteria or StoppingCriteria()
        self._check_criteria(crit)
        sdt = acc_dtype(np.dtype(self.problem.vdtype))
        dev = self.device_args(Bg, x0)
        b, x0_st, la, ga, sidx, gsrc, gval, scnt, rcnt = dev
        program = self._program_for(int(Bg.shape[1]))
        return program.lower(la, ga, sidx, gsrc, gval, scnt, rcnt, b,
                             x0_st, jnp.asarray(crit.residual_atol, sdt),
                             jnp.asarray(crit.residual_rtol, sdt),
                             jnp.int32(crit.maxits),
                             unbounded=crit.unbounded)

    def _check_criteria(self, crit):
        if crit.needs_diff:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "the batched tiers support residual criteria only")

    # -- solve --------------------------------------------------------------

    def solve(self, B_global, x0=None,
              criteria: StoppingCriteria | None = None,
              raise_on_divergence: bool = True, warmup: int = 0,
              host_result: bool = True):
        Bg = np.asarray(B_global)
        if Bg.ndim == 1:
            Bg = Bg[:, None]
        nrhs = int(Bg.shape[1])
        crit = criteria or StoppingCriteria()
        st = self.stats
        st.criteria = crit
        if nrhs == 1:
            inner = self._inner()
            x = inner.solve(Bg[:, 0], x0=_squeeze_col(x0),
                            criteria=crit,
                            raise_on_divergence=raise_on_divergence,
                            warmup=warmup, host_result=host_result)
            self.stats = st = inner.stats
            self.last_trace = inner.last_trace
            st.batch = {"nrhs": 1, "mode": "pipelined"
                        if self.pipelined else "batched",
                        "iterations": [int(st.niterations)],
                        "rnrm2": [float(st.rnrm2)],
                        "converged": [bool(st.converged)],
                        "iterations_max": int(st.niterations),
                        "iterations_sum": int(st.niterations)}
            return (np.asarray(x).reshape(-1, 1) if host_result
                    else x)
        self._check_criteria(crit)
        if self.ckpt is not None:
            return self._solve_ckpt(Bg, x0, crit, raise_on_divergence,
                                    warmup, host_result)
        from acg_tpu import telemetry
        t_xfer = time.perf_counter()
        with telemetry.annotate("transfer"):
            dev = self.device_args(Bg, x0)
            b, x0_st, la, ga, sidx, gsrc, gval, scnt, rcnt = dev
        telemetry.add_timing(st, "transfer",
                             time.perf_counter() - t_xfer)
        sdt = acc_dtype(np.dtype(self.problem.vdtype))
        program = self._program_for(nrhs)
        args = (la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0_st,
                jnp.asarray(crit.residual_atol, sdt),
                jnp.asarray(crit.residual_rtol, sdt),
                jnp.int32(crit.maxits))
        from acg_tpu._platform import block_until_ready_works, device_sync
        block_until_ready_works()
        t_warm = time.perf_counter()
        with telemetry.annotate("compile"):
            for _ in range(max(warmup, 0)):
                device_sync(program(*args,
                                    unbounded=crit.unbounded)[0])
        if warmup > 0:
            telemetry.add_timing(st, "compile",
                                 time.perf_counter() - t_warm)
        t0 = time.perf_counter()
        with telemetry.annotate("solve"):
            out = program(*args, unbounded=crit.unbounded)
            device_sync(out[0])
        t_solve = time.perf_counter() - t0
        st.tsolve += t_solve
        telemetry.add_timing(st, "solve", t_solve)
        tbuf = out[8] if self.trace else None
        self._finish_stats(out, t_solve, nrhs, tbuf)
        x_st = out[0]
        x = self._gather_cols(get_global(x_st)) if host_result else x_st
        if host_result:
            st.fexcept_arrays = [x]
        if not st.converged and raise_on_divergence:
            raise NotConvergedError(
                f"{st.niterations} iterations, "
                f"{st.batch['unconverged']} of {nrhs} RHS unconverged")
        return x

    def _finish_stats(self, out, t_solve, nrhs, tbuf=None,
                      executed=None) -> None:
        from acg_tpu import metrics, observatory, telemetry
        st = self.stats
        iters = np.asarray(out[1]).astype(int).tolist()
        k_total = int(out[2]) if executed is None else int(executed)
        rn = [float(v) for v in np.asarray(out[3])]
        conv = [bool(v) for v in np.asarray(out[7])]
        st.nsolves += 1
        st.niterations = k_total
        st.ntotaliterations += k_total
        st.r0nrm2 = float(np.max(np.asarray(out[4])))
        st.bnrm2 = float(np.max(np.asarray(out[5])))
        st.x0nrm2 = float(np.max(np.asarray(out[6])))
        st.rnrm2 = float(max(rn))
        st.dxnrm2 = float("inf")
        st.converged = all(conv)
        st.batch = {
            "nrhs": nrhs,
            "mode": "pipelined" if self.pipelined else "batched",
            "iterations": iters,
            "iterations_max": int(max(iters) if iters else 0),
            "iterations_sum": int(sum(iters)),
            "rnrm2": rn,
            "converged": conv,
            "unconverged": int(sum(1 for c in conv if not c)),
        }
        if tbuf is not None:
            st.trace = self.last_trace = \
                telemetry.BatchedConvergenceTrace.from_ring(
                    np.asarray(tbuf), k_total,
                    solver="dist-cg-batched-pipelined"
                    if self.pipelined else "dist-cg-batched")
        metrics.record_solve(t_solve, k_total, st.converged,
                             solver="dist-cg-batched")
        observatory.note_batch(nrhs, rn, conv)
        self._account_ops(st, k_total, nrhs)

    def _account_ops(self, st, k_total: int, nrhs: int) -> None:
        prob = self.problem
        dtype = np.dtype(prob.vdtype)
        n = prob.n
        st.nflops += (cg_flops_per_iteration(prob.nnz_total, n,
                                             self.pipelined) * k_total
                      + 3.0 * prob.nnz_total + 2.0 * n) * nrhs
        dbl = dtype.itemsize
        mat_dbl = np.dtype(prob.dtype).itemsize
        idx_b = 0 if prob.local.format == "dia" else 4
        st.ops["gemv"].add(k_total + 1, 0.0,
                           (prob.nnz_total * (mat_dbl + idx_b)
                            + 2 * n * dbl * nrhs) * (k_total + 1))
        st.ops["dot"].add(k_total, 0.0, 2 * n * dbl * nrhs * k_total)
        st.ops["axpy"].add(3 * k_total, 0.0,
                           3 * n * dbl * nrhs * 3 * k_total)
        # the B-invariant property in the ledger: collective COUNT
        # unchanged, payload widened to B scalars
        nred = 1 if self.pipelined else 2
        st.ops["allreduce"].add(nred * k_total, 0.0,
                                8 * nrhs * nred * k_total)
        halo_total = getattr(prob, "halo_send_total", None)
        if halo_total is None:
            halo_total = sum(int(s.halo.total_send) for s in prob.subs
                             if s.halo is not None)
        st.ops["halo"].add(k_total + 1, 0.0,
                           halo_total * dbl * nrhs * (k_total + 1))

    # -- survivability: chunked batched dist solve --------------------------

    def _solve_ckpt(self, Bg, x0, crit, raise_on_divergence: bool,
                    warmup: int, host_result: bool):
        """Chunked batched SPMD solve with per-part per-RHS snapshot
        leaves ((nparts, pad, B) stacks + the row-permutation sidecar)
        -- a whole BATCH survives preemption, and
        ``--resume-repartition`` reassembles every column onto a
        different mesh through checkpoint.reassemble_global's batched
        path."""
        from acg_tpu import checkpoint as ckpt_mod
        from acg_tpu import metrics, observatory, telemetry
        from acg_tpu._platform import block_until_ready_works, device_sync
        cfg = self.ckpt
        st = self.stats
        prob = self.problem
        nrhs = int(Bg.shape[1])
        dtype = np.dtype(prob.vdtype)
        sdt = acc_dtype(dtype)
        put = functools.partial(put_global, sharding=self._sharding)
        b_crc = ckpt_mod.vector_checksum(np.asarray(Bg))
        names = ckpt_mod.batched_carry_names(False)
        dev = self.device_args(Bg, x0)
        b, x0_st, la, ga, sidx, gsrc, gval, scnt, rcnt = dev
        fixed = (la, ga, sidx, gsrc, gval, scnt, rcnt, b)
        program = self._program_for(nrhs, state_io=True)

        def run(x_cur, atol_cols, rtol, m, carry):
            # per-RHS absolute targets ride the atol argument whole:
            # resumed chunks keep every column's ORIGINAL tolerance
            # (never re-baselined against an already-small residual)
            out = program(*fixed, x_cur,
                          jnp.asarray(atol_cols, dtype=sdt),
                          jnp.asarray(rtol, dtype=sdt), jnp.int32(m),
                          unbounded=crit.unbounded, carry=carry)
            ring = out[8] if self.trace else None
            core = out[-5:]
            return out[:8], ring, core

        consumed = 0
        executed = 0
        resumed_from = None
        repartitioned = None
        carry = None
        x_cur = x0_st
        abs_tol = None
        first_r0 = None
        snap = cfg.resume
        if snap is not None:
            ckpt_mod.validate_resume(
                snap, tier=self._ckpt_tier, pipelined=False,
                precond=None, n=int(prob.n), dtype=dtype, b_crc=b_crc,
                nparts=int(prob.nparts),
                repartition=cfg.repartition, nrhs=nrhs)
            if cfg.repartition:
                snap, repartitioned = ckpt_mod.apply_repartition(
                    snap, tier=self._ckpt_tier,
                    nparts=int(prob.nparts), stats=st,
                    precond_spec=None)
                arrs_g = {}
                for nm, a in snap.arrays.items():
                    a = np.asarray(a)
                    if nm in ckpt_mod.BATCHED_COL_LEAVES or a.ndim < 2:
                        arrs_g[nm] = a
                    else:
                        arrs_g[nm] = self._scatter_cols(a, a.dtype)
                snap = ckpt_mod.SolverSnapshot(meta=snap.meta,
                                               arrays=arrs_g)
            consumed = resumed_from = snap.iteration
            sm = snap.meta
            abs_tol = np.asarray(sm["abs_tol"], dtype=np.float64)
            first_r0 = np.asarray(sm["r0nrm2"], dtype=np.float64)
            x_cur = put(np.asarray(snap.arrays["x"], dtype=dtype))
            carry = tuple(
                jnp.asarray(snap.arrays[nm]) if nm in
                ckpt_mod.BATCHED_COL_LEAVES
                else put(np.asarray(snap.arrays[nm], dtype=dtype))
                for nm in names[1:])
            metrics.record_resume()
            telemetry.record_event(
                st, "resume",
                f"resumed batched dist solve ({nrhs} RHS) at "
                f"iteration {consumed}")
        block_until_ready_works()
        seq = 0
        nsnaps = 0
        ck_secs = 0.0
        res = None
        t0 = time.perf_counter()
        with telemetry.annotate("solve"):
            while True:
                remaining = crit.maxits - consumed
                if remaining <= 0:
                    break
                m = min(cfg.chunk_for(None), remaining)
                if abs_tol is None:
                    res, tbuf, core = run(
                        x_cur, np.full(nrhs, crit.residual_atol),
                        crit.residual_rtol, m, carry)
                else:
                    res, tbuf, core = run(x_cur, abs_tol, 0.0, m,
                                          carry)
                device_sync(res[0])
                k_chunk = int(res[2])
                consumed += k_chunk
                executed += k_chunk
                if first_r0 is None:
                    first_r0 = np.asarray(res[4], dtype=np.float64)
                    abs_tol = np.maximum(crit.residual_atol,
                                         crit.residual_rtol * first_r0)
                if self.trace and tbuf is not None:
                    st.trace = self.last_trace = \
                        telemetry.BatchedConvergenceTrace.from_ring(
                            np.asarray(tbuf), k_chunk,
                            solver="dist-cg-batched",
                            offset=consumed - k_chunk)
                rn = np.asarray(res[3])
                conv = np.asarray(res[7])
                worst = (float(np.max(rn[~conv])) if (~conv).any()
                         else float(np.max(rn)))
                observatory.note_chunk(self._ckpt_tier, consumed,
                                       worst,
                                       abs_tol=float(np.max(abs_tol)),
                                       rtol=crit.residual_rtol)
                observatory.note_batch(nrhs, [float(v) for v in rn],
                                       [bool(v) for v in conv])
                finished = (consumed >= crit.maxits if crit.unbounded
                            else bool(conv.all()))
                x_cur = res[0]
                carry = core
                if cfg.path is not None and not finished:
                    t_ck = time.perf_counter()
                    arrs = {"x": np.asarray(get_global(res[0]))}
                    for nm, leaf in zip(names[1:], core):
                        arrs[nm] = np.asarray(
                            get_global(leaf) if nm not in
                            ckpt_mod.BATCHED_COL_LEAVES else leaf)
                    seq += 1
                    meta = {
                        "tier": self._ckpt_tier,
                        "pipelined": False,
                        "precond": None,
                        "n": int(prob.n),
                        "nparts": int(prob.nparts),
                        "nrhs": nrhs,
                        "dtype": str(dtype),
                        "iteration": consumed,
                        "seq": seq,
                        "abs_tol": [float(v) for v in abs_tol],
                        "bnrm2": [float(v) for v in np.asarray(res[5])],
                        "x0nrm2": [float(v)
                                   for v in np.asarray(res[6])],
                        "r0nrm2": [float(v) for v in first_r0],
                        "b_crc": b_crc,
                        "trace_tail": [],
                    }
                    rp = prob.row_permutation()
                    if rp is not None:
                        arrs["_rowperm"] = rp
                        meta["part_rows"] = prob.part_rows()
                    ckpt_mod.agree_seq(seq, consumed)
                    if jax.process_index() == 0:
                        nbytes = ckpt_mod.save_snapshot(cfg.path, meta,
                                                        arrs)
                    else:
                        nbytes = 0
                    dt = time.perf_counter() - t_ck
                    ck_secs += dt
                    telemetry.add_timing(st, "ckpt", dt)
                    metrics.record_snapshot(nbytes, dt)
                    nsnaps += 1
                if finished:
                    break
        if res is None:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"snapshot iteration {consumed} already meets the "
                f"iteration cap {crit.maxits}; raise --max-iterations "
                f"to continue this solve")
        t_solve = time.perf_counter() - t0 - ck_secs
        st.tsolve += t_solve
        telemetry.add_timing(st, "solve", t_solve)
        self._finish_stats(res, t_solve, nrhs, None, executed=executed)
        st.ckpt = {
            "path": cfg.path,
            "every": int(cfg.every),
            "snapshots": nsnaps,
            "iteration": consumed,
            "rollbacks": 0,
        }
        if resumed_from is not None:
            st.ckpt["resumed_from"] = resumed_from
        if repartitioned is not None:
            st.ckpt["repartitioned_from"] = repartitioned
        x_st = res[0]
        x = self._gather_cols(get_global(x_st)) if host_result else x_st
        if host_result:
            st.fexcept_arrays = [x]
        if not st.converged and raise_on_divergence:
            raise NotConvergedError(
                f"{executed} iterations, "
                f"{st.batch['unconverged']} of {nrhs} RHS unconverged")
        return x
