"""Distributed CG over a TPU device mesh.

The multi-device counterpart of :mod:`acg_tpu.solvers.jax_cg`, rebuilding
the reference's distributed solve paths (``acgsolvercuda_solvempi``,
``_solve_pipelined``, ``cgcuda.c:403-1917``; device-initiated variants
``cg-kernels-cuda.cu:627-1688``) in the execution model XLA natively
provides: ONE compiled SPMD program containing the whole solve loop --
which is precisely the reference's monolithic persistent-kernel design,
with `lax.psum` in place of NVSHMEM allreduce and an `all_to_all` halo in
place of put-with-signal neighbour messaging.

Data layout (host-built by :class:`DistributedProblem`):
  * every per-part array is padded to the max size across parts (XLA needs
    identical shapes per shard; the reference does the same max-sizing for
    NVSHMEM symmetric buffers, ``halo.c:883-887``), stacked on a leading
    ``parts`` axis, and sharded over the 1-D solve mesh;
  * vectors are `[owned | padding]`; padding rows of the matrix blocks are
    all-zero so padded entries stay exactly zero through every update and
    reduction -- no masks needed anywhere in the loop;
  * the local (owned x owned) and off-diagonal (owned x ghost) blocks are
    separate (the reference's ``f*``/``o*`` split), so XLA can overlap the
    halo all_to_all with the local-block SpMV -- the same communication/
    computation overlap the reference schedules by hand with streams and
    events (``cgcuda.c:855-899``);
  * the local block is stored as gather-free DIA planes whenever the
    partition keeps it banded (:class:`StackedLocalBlock`; owned rows are
    re-sorted into natural order for this -- ``graph.reorder_owned_
    natural``), with ELL gather planes as the general fallback; the ghost
    block is compressed to the coupled (border) rows only
    (:class:`StackedGhostBlock`).
"""

from __future__ import annotations

import dataclasses
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from acg_tpu._platform import shard_map as _shard_map
from acg_tpu.errors import (AcgError, BreakdownError, ErrorCode,
                            NotConvergedError)
from acg_tpu.graph import (Subdomain, partition_matrix, reorder_owned_natural,
                           scatter_vector)
from acg_tpu.ops.spmv import (acc_dtype, csr_diag_offsets, dia_mv,
                              dia_planes_fixed, ell_planes_from_csr)
from acg_tpu.parallel.halo import DeviceHaloPlan, build_device_halo, halo_exchange
from acg_tpu.parallel.halo_dma import halo_exchange_dma
from acg_tpu.parallel.mesh import PARTS_AXIS, solve_mesh
from acg_tpu.parallel.reductions import make_pdot, make_pdotk
from acg_tpu.parallel.multihost import get_global, put_global
from acg_tpu.solvers.jax_cg import _breakdown_guard, _iterate
from acg_tpu.solvers.stats import (SolverStats, StoppingCriteria,
                                   cg_flops_per_iteration)

# the reference's --comm spellings mapped onto our two transports
# (cuda/acg-cuda.c:321-377): ONE copy, shared by the CLI, the explain
# tier and the commbench observatory
COMM_ALIASES = {"mpi": "xla", "nccl": "xla", "nvshmem": "dma"}


def resolve_comm(name: str) -> str:
    """Transport for a dist solver from a --comm spelling; ``none``
    (the CLI's single-device selector) resolves to the xla transport
    for analysis passes that build a mesh tier regardless."""
    c = COMM_ALIASES.get(str(name), str(name))
    return "xla" if c == "none" else c


def _ell_mv(data: jax.Array, cols: jax.Array, x: jax.Array) -> jax.Array:
    return jnp.einsum("nk,nk->n", data, x[cols],
                      preferred_element_type=acc_dtype(x.dtype)
                      ).astype(x.dtype)


@dataclasses.dataclass
class StackedLocalBlock:
    """Per-part owned x owned blocks, stacked over the mesh (leading axis
    = parts) in the fastest eligible device format.

    ``"dia"``: gather-free diagonal planes (one (P, nrows) array per
    offset; the union of all parts' offsets is stored so shapes are
    mesh-uniform).  Chosen when the partition keeps local blocks banded --
    contiguous parts of a banded matrix (``partition_rows_band``) with
    owned rows in natural order.  ``"ell"``: row-padded gather planes
    ``(data, cols)``, the general fallback (scattered partitions).
    ``"binnedell"``: the length-binned layout of
    :class:`acg_tpu.ops.spmv.BinnedEllMatrix` stacked per part
    (mesh-uniform per-bin row maxima + a padded COO hub tail) -- chosen
    by the same histogram rule as the single-device ``auto`` when
    plain-ELL padding waste blows past its limit (power-law /
    SuiteSparse-class workloads; the reference's merge-CSR load-balance
    goal, ``cg-kernels-cuda.cu:340-441``, round-4 verdict item 3).
    """

    format: str      # "dia" | "ell" | "binnedell" | "matfree"
    arrays: tuple    # dia: ndiags x (P, nrows); ell: (data (P,nrows,K), cols)
    #                  binnedell: (bin_rows, bin_data, bin_cols tuples,
    #                              tail_rows, tail_cols, tail_vals)
    #                  matfree: (row0 (P,1), nowned (P,1), *tables (P,L))
    offsets: tuple   # dia/matfree: static diagonal offsets, ascending
    nrows: int
    bin_ks: tuple = ()   # binnedell only: static K_b per bin
    # matfree only (acg_tpu.ops.operator / arm_matfree): the stencil
    # operator TEMPLATE -- static metadata (kind, grid, dtype) keying
    # the in-shard plane generation; its coefficient tables ride
    # ``arrays`` stacked per part so they shard like every other block
    operator: object = None

    def gen_planes(self, arrays):
        """Matfree: this shard's LOCAL-block DIA planes, generated from
        the stacked (row0, nowned, *tables) arrays -- global stencil
        values at rows [row0, row0 + nrows) masked to the owned x owned
        window, bitwise-equal to what ``dia_planes_fixed`` would have
        assembled (out-of-part couplings live in the ghost block,
        padding rows are zero)."""
        from acg_tpu.ops.operator import stencil_planes
        op = self.operator
        row0 = arrays[0].reshape(-1)[0]
        nown = arrays[1].reshape(-1)[0]
        return stencil_planes(op.kind, op.grid, self.offsets,
                              tuple(arrays[2:]), self.nrows, op.dtype,
                              row0=row0, nowned=nown)

    def shard_mv(self, arrays, x):
        """y = A_local @ x for one shard (arrays = leading axis stripped)."""
        if self.format == "matfree":
            # the matrix-free stencil tier: plane values generated in
            # the shard (fused by XLA into the accumulate), then the
            # SAME dia_mv accumulation as the assembled DIA path --
            # zero matrix HBM traffic, bitwise-equal trajectories
            return dia_mv(self.gen_planes(arrays), self.offsets,
                          self.nrows, x)
        if self.format == "dia":
            return dia_mv(arrays, self.offsets, self.nrows, x)
        if self.format == "binnedell":
            bin_rows, bin_data, bin_cols, t_rows, t_cols, t_vals = arrays
            adt = acc_dtype(x.dtype)
            y = jnp.zeros((self.nrows,), dtype=adt)
            for rows, data, cols in zip(bin_rows, bin_data, bin_cols):
                contrib = jnp.einsum("mk,mk->m", data, x[cols],
                                     preferred_element_type=adt)
                # padding rows index nrows -> dropped by the jit
                # scatter's OOB mode (NOT unique_indices: every padding
                # row shares that id)
                y = y.at[rows].add(contrib)
            if t_vals.shape[-1]:
                prod = t_vals.astype(adt) * x[t_cols].astype(adt)
                y = y.at[t_rows].add(prod)
            return y.astype(x.dtype)
        data, cols = arrays
        return _ell_mv(data, cols, x)


@dataclasses.dataclass
class StackedGhostBlock:
    """Per-part owned x ghost off-diagonal blocks, compressed to the rows
    that actually touch ghosts (the reference stores its ``o*`` block over
    border rows only, ``symcsrmatrix.h:249-292``; here the coupled-row
    list replaces the contiguous border range).  SpMV gathers ghost values
    for ``bmax`` coupled rows and scatter-adds their contributions --
    O(border) work instead of O(owned)."""

    rows: jax.Array   # (P, bmax) int32, ascending; padding = nrows (dropped)
    data: jax.Array   # (P, bmax, Kg)
    cols: jax.Array   # (P, bmax, Kg) int32 into the ghost vector
    nrows: int
    bmax: int

    def shard_mv(self, arrays, xg):
        rows, data, cols = arrays
        contrib = jnp.einsum("bk,bk->b", data, xg[cols],
                             preferred_element_type=acc_dtype(xg.dtype)
                             ).astype(xg.dtype)
        # padding rows index nrows: out of bounds -> dropped by scatter
        return jnp.zeros((self.nrows,), xg.dtype).at[rows].add(
            contrib, indices_are_sorted=True)


@dataclasses.dataclass(frozen=True)
class UniformShapes:
    """Mesh-uniform sizing agreed across controllers for the LOCAL-READ
    flow (each controller sees only its own parts): the union DIA offset
    set (or None -> ELL), padded widths, and halo maxima.  The analog of
    the reference's max-allreduce symmetric-buffer sizing
    (``halo.c:883-887``), computed by one small allgather."""

    offsets: tuple | None   # DIA offsets union, or None for the ELL path
    Kl: int                 # max local-block row width
    bmax: int               # max coupled (border) rows per part
    Kg: int                 # max ghost-block row width
    maxcnt: int             # max per-neighbour halo send count
    nmax_ghost: int         # max ghost count per part
    nnz_total: int
    halo_send_total: int = 0   # sum of per-part halo send entries
    # binned-ELL sizing (round-4 verdict item 3): per-BELL_WIDTHS-bin
    # max row count over all parts, and the max hub-tail nnz; None when
    # the plain-ELL waste rule keeps the ell layout
    bell_ms: tuple | None = None
    bell_tail: int = 0


def _agree_uniform_shapes(subs_owned, nparts: int,
                          max_diags: int = 80,
                          dia_waste_limit: float = 3.0,
                          ell_waste_limit: float = 3.0,
                          nmax_owned: int = 0) -> UniformShapes:
    """Compute this controller's local stats and allgather-max/union them
    so every controller derives the IDENTICAL stacked shapes.  The
    payload is one fixed-size int64 vector per process."""
    import jax

    from acg_tpu.ops.spmv import BELL_WIDTHS

    offs = np.unique(np.concatenate(
        [csr_diag_offsets(s.A_local) for s in subs_owned]
        or [np.zeros(0, np.int64)]))
    Kl = max((int(np.diff(s.A_local.indptr).max(initial=0))
              for s in subs_owned), default=0)
    bmax = max((int(np.count_nonzero(np.diff(s.A_ghost.indptr)))
                for s in subs_owned), default=0)
    Kg = max((int(np.diff(s.A_ghost.indptr).max(initial=0))
              for s in subs_owned), default=0)
    maxcnt = max((int(c) for s in subs_owned for c in s.halo.send_counts),
                 default=0)
    nmax_ghost = max((s.nghost for s in subs_owned), default=0)
    nnz = sum(int(s.A_local.nnz + s.A_ghost.nnz) for s in subs_owned)
    # LOCAL-block-only nnz, agreed separately: the ELL/binned-ELL waste
    # ratio concerns the local block's padding against its own nnz, and
    # the full-view flow (_stack_local_blocks) computes it that way --
    # using the ghost-inclusive total here made borderline matrices pick
    # plain ELL in the local-read flow while the full-view flow binned
    # them (ADVICE r5)
    nnz_local = sum(int(s.A_local.nnz) for s in subs_owned)
    send_total = sum(int(s.halo.total_send) for s in subs_owned)
    # binned-ELL sizing: per-bin row-count max and hub-tail nnz max over
    # this controller's parts (the bin histogram of each local block)
    nbins = len(BELL_WIDTHS)
    bell = _bell_histogram([s.A_local for s in subs_owned])
    cap = 2 * max_diags
    too_many = offs.size > cap
    payload = np.full(cap + 9 + nbins + 1, np.iinfo(np.int64).min,
                      dtype=np.int64)
    payload[:min(offs.size, cap)] = offs[:cap]
    payload[cap:cap + 9] = (offs.size if not too_many else cap + 1,
                            Kl, bmax, Kg, maxcnt, nmax_ghost, nnz,
                            send_total, nnz_local)
    payload[cap + 9:] = bell
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        gathered = np.asarray(
            multihost_utils.process_allgather(payload, tiled=False))
    else:
        gathered = payload[None]
    all_offs = np.unique(np.concatenate(
        [g[:cap][g[:cap] != np.iinfo(np.int64).min] for g in gathered]
        or [np.zeros(0, np.int64)]))
    counts = gathered[:, cap]
    Kl = int(gathered[:, cap + 1].max())
    bmax = int(gathered[:, cap + 2].max())
    Kg = int(gathered[:, cap + 3].max())
    maxcnt = int(gathered[:, cap + 4].max())
    nmax_ghost = int(gathered[:, cap + 5].max())
    nnz_total = int(gathered[:, cap + 6].sum())
    halo_send_total = int(gathered[:, cap + 7].sum())
    nnz_local_total = int(gathered[:, cap + 8].sum())
    bell_all = gathered[:, cap + 9:].max(axis=0)
    dia_ok = (not (counts > cap).any() and all_offs.size <= max_diags
              and nnz_total
              and (all_offs.size * nmax_owned * nparts
                   <= dia_waste_limit * nnz_total))
    # the single-device auto histogram rule (ops.spmv.device_matrix_
    # from_csr): when plain-ELL padding waste blows its limit, take the
    # binned layout.  Every controller computes this from the same
    # agreed scalars, so the format decision is mesh-uniform -- and the
    # waste ratio uses the LOCAL-block nnz, the same definition the
    # full-view flow applies, so borderline matrices pick the same
    # format on both ingest paths (ADVICE r5)
    bell_ok = (not dia_ok and nnz_local_total
               and Kl * nmax_owned * nparts
               > ell_waste_limit * nnz_local_total)
    return UniformShapes(
        offsets=tuple(int(o) for o in all_offs) if dia_ok else None,
        Kl=Kl, bmax=bmax, Kg=Kg, maxcnt=maxcnt, nmax_ghost=nmax_ghost,
        nnz_total=nnz_total, halo_send_total=halo_send_total,
        bell_ms=tuple(int(m) for m in bell_all[:nbins]) if bell_ok
        else None,
        bell_tail=int(bell_all[nbins]) if bell_ok else 0)


def _bell_histogram(blocks) -> np.ndarray:
    """``(len(BELL_WIDTHS) + 1,)`` int64: per-bin MAX row count over the
    given local blocks, hub-tail max nnz last.  The one binning rule
    shared by the uniform-shape agreement and the stacking itself --
    they must stay bit-identical or the agreed bin sizes overflow on
    the local-read flow."""
    from acg_tpu.ops.spmv import BELL_WIDTHS

    nbins = len(BELL_WIDTHS)
    out = np.zeros(nbins + 1, dtype=np.int64)
    widths = np.asarray(BELL_WIDTHS)
    for b in blocks:
        if b is None:
            continue
        row_nnz = np.diff(b.indptr)
        bidx = np.searchsorted(widths, row_nnz)
        cnt = np.bincount(np.minimum(bidx, nbins), minlength=nbins + 1)
        out[:nbins] = np.maximum(out[:nbins], cnt[:nbins])
        out[nbins] = max(out[nbins], int(row_nnz[bidx >= nbins].sum()))
    return out


def _stack_bell_blocks(blocks, nrows_pad: int, dtype,
                       bin_ms, tail_max: int) -> StackedLocalBlock:
    """Stack per-part local blocks in the length-binned ELL layout with
    MESH-UNIFORM shapes: bin b holds ``bin_ms[b]`` row slots per part
    (the max over parts; absent rows pad with row id ``nrows_pad`` ->
    dropped by the scatter), the hub tail ``tail_max`` COO slots.  The
    distributed restatement of :func:`acg_tpu.ops.spmv.
    binned_ell_from_csr` (round-4 verdict item 3; ref
    ``cg-kernels-cuda.cu:340-441``)."""
    from acg_tpu.ops.spmv import BELL_WIDTHS

    P = len(blocks)
    npdtype = np.dtype(dtype)
    widths = np.asarray(BELL_WIDTHS)
    live = [b for b in range(widths.size) if bin_ms[b]]
    bin_rows = [np.full((P, bin_ms[b]), nrows_pad, np.int32) for b in live]
    bin_data = [np.zeros((P, bin_ms[b], widths[b]), npdtype) for b in live]
    bin_cols = [np.zeros((P, bin_ms[b], widths[b]), np.int32) for b in live]
    T = int(tail_max)
    t_rows = np.full((P, T), nrows_pad, np.int32)
    t_cols = np.zeros((P, T), np.int32)
    t_vals = np.zeros((P, T), npdtype)
    for p, blk in enumerate(blocks):
        if blk is None:
            continue
        indptr = np.asarray(blk.indptr)
        vals = np.asarray(blk.data)
        colidx = np.asarray(blk.indices)
        row_nnz = np.diff(indptr)
        bidx = np.searchsorted(widths, row_nnz)
        for i, b in enumerate(live):
            rows_b = np.flatnonzero(bidx == b).astype(np.int32)
            if rows_b.size == 0:
                continue
            nnz_b = row_nnz[rows_b]
            flat_r = np.repeat(np.arange(rows_b.size), nnz_b)
            flat_p = (np.arange(nnz_b.sum())
                      - np.repeat(np.cumsum(nnz_b) - nnz_b, nnz_b))
            src = (np.repeat(indptr[rows_b], nnz_b) + flat_p).astype(np.int64)
            bin_rows[i][p, : rows_b.size] = rows_b
            bin_data[i][p][flat_r, flat_p] = vals[src]
            bin_cols[i][p][flat_r, flat_p] = colidx[src]
        hub = np.flatnonzero(bidx >= widths.size)
        if hub.size:
            t_r = np.repeat(hub, row_nnz[hub]).astype(np.int32)
            t_src = np.concatenate(
                [np.arange(indptr[r], indptr[r + 1]) for r in hub])
            t_rows[p, : t_r.size] = t_r
            t_cols[p, : t_r.size] = colidx[t_src]
            t_vals[p, : t_r.size] = vals[t_src]
    return StackedLocalBlock(
        format="binnedell",
        arrays=(tuple(bin_rows), tuple(bin_data), tuple(bin_cols),
                t_rows, t_cols, t_vals),
        offsets=(), nrows=nrows_pad,
        bin_ks=tuple(int(widths[b]) for b in live))


def _stack_local_blocks(subs, nmax_owned: int, dtype,
                        max_diags: int = 80,  # headroom over spmv.MAX_DIAGS:
                        # the union of per-part offset sets can exceed any
                        # single part's diagonal count
                        dia_waste_limit: float = 3.0,
                        ell_waste_limit: float = 3.0,
                        global_csr=None,
                        uniform: UniformShapes | None = None
                        ) -> StackedLocalBlock:
    """Stacked arrays are HOST numpy (calloc-backed zeros, filled only
    for parts whose blocks exist): non-owned parts of a multi-controller
    build never touch their pages, so host RSS is O(owned/P); the device
    placement happens later through ``put_global``'s per-shard slicing
    (``DistCGSolver.device_args``).

    With restricted builds (some ``A_local is None``) the mesh-uniform
    format decision and shape bounds come from ``global_csr`` -- every
    controller must pick identical offsets/K."""
    blocks = [s.A_local for s in subs]
    built = [b for b in blocks if b is not None]
    npdtype = np.dtype(dtype)
    if uniform is not None:
        # local-read flow: shapes (and the format decision) pre-agreed
        # across controllers
        if uniform.offsets is not None:
            offs = np.asarray(uniform.offsets, dtype=np.int64)
            nnz = uniform.nnz_total
        else:
            if uniform.bell_ms is not None:
                return _stack_bell_blocks(blocks, nmax_owned, dtype,
                                          uniform.bell_ms,
                                          uniform.bell_tail)
            offs = np.zeros(0, np.int64)
            nnz = 0  # force the ELL path
        Kl = uniform.Kl
    elif global_csr is not None:
        # restricted build: the local blocks of OTHER controllers are
        # invisible, so the mesh-uniform offset set must be derivable
        # from global structure alone.  That is only sound when every
        # part's owned rows form a contiguous natural-order range (band
        # partitions): then local diagonals are a subset of the global
        # ones.  Scattered (graph/metis) partitions have local-index
        # diagonals unrelated to the global set -> ELL path.
        contiguous = all(
            s.owned_order == "natural" and (s.nowned == 0 or (
                int(s.global_ids[s.nowned - 1]) - int(s.global_ids[0]) + 1
                == s.nowned))
            for s in subs)
        offs = (csr_diag_offsets(global_csr) if contiguous
                else np.zeros(0, np.int64))
        nnz = int(global_csr.nnz) if contiguous else 0
        Kl = int(np.diff(global_csr.indptr).max(initial=0))
    else:
        offs = np.unique(np.concatenate(
            [csr_diag_offsets(b) for b in built] or [np.zeros(1, np.int64)]))
        nnz = sum(int(b.nnz) for b in built)
        Kl = max((int(np.diff(b.indptr).max(initial=0)) for b in built),
                 default=0)
    if (nnz and offs.size <= max_diags
            and offs.size * nmax_owned * len(blocks) <= dia_waste_limit * nnz):
        planes = np.zeros((offs.size, len(blocks), nmax_owned),
                          dtype=npdtype)
        for p, b in enumerate(blocks):
            if b is not None:
                planes[:, p, :] = dia_planes_fixed(b, offs, nmax_owned)
        return StackedLocalBlock(format="dia",
                                 arrays=tuple(planes[d]
                                              for d in range(offs.size)),
                                 offsets=tuple(int(o) for o in offs),
                                 nrows=nmax_owned)
    if (uniform is None and global_csr is None and nnz
            and Kl * nmax_owned * len(blocks) > ell_waste_limit * nnz):
        # the single-device auto histogram rule: plain-ELL padding waste
        # past its limit -> length-binned layout.  (Restricted builds --
        # global_csr set -- keep ELL: per-part LOCAL row widths are not
        # derivable from global structure on the controllers that cannot
        # see the blocks, so a mesh-uniform bin sizing does not exist
        # there; the local-read flow agrees bins via its allgather.)
        bell = _bell_histogram(built)
        return _stack_bell_blocks(blocks, nmax_owned, dtype,
                                  tuple(int(m) for m in bell[:-1]),
                                  int(bell[-1]))
    Kl = max(Kl, 1)
    ld = np.zeros((len(blocks), nmax_owned, Kl), dtype=npdtype)
    lc = np.zeros((len(blocks), nmax_owned, Kl), dtype=np.int32)
    for p, b in enumerate(blocks):
        if b is None:
            continue
        d, c = ell_planes_from_csr(b.indptr, b.indices, b.data, nmax_owned,
                                   pad_k=Kl)
        ld[p], lc[p] = d.astype(npdtype), c
    return StackedLocalBlock(format="ell", arrays=(ld, lc),
                             offsets=(), nrows=nmax_owned)


def _stack_ghost_blocks(subs, nmax_owned: int, dtype,
                        global_csr=None,
                        uniform: UniformShapes | None = None
                        ) -> StackedGhostBlock:
    """Host-numpy ghost blocks (see ``_stack_local_blocks``); with
    restricted builds the uniform bmax/Kg bounds come from the global
    structure (border counts are known for every part; the global max
    row length bounds any ghost row's length) or the pre-agreed
    ``uniform`` shapes (local-read flow)."""
    npdtype = np.dtype(dtype)
    coupled = [None if s.A_ghost is None
               else np.flatnonzero(np.diff(s.A_ghost.indptr)) for s in subs]
    if uniform is not None:
        bmax = uniform.bmax or 1
        Kg = uniform.Kg or 1
    elif global_csr is not None:
        bmax = max((s.nborder for s in subs), default=0) or 1
        Kg = int(np.diff(global_csr.indptr).max(initial=0)) or 1
    else:
        bmax = max((r.size for r in coupled if r is not None), default=0) or 1
        Kg = max((int(np.diff(s.A_ghost.indptr).max(initial=0))
                  for s in subs if s.A_ghost is not None), default=0) or 1
    P = len(subs)
    rows = np.full((P, bmax), nmax_owned, dtype=np.int32)  # pad = OOB drop
    data = np.zeros((P, bmax, Kg), dtype=npdtype)
    cols = np.zeros((P, bmax, Kg), dtype=np.int32)
    for p, (s, ri) in enumerate(zip(subs, coupled)):
        if ri is None or ri.size == 0:
            continue
        sub = s.A_ghost[ri]
        d, c = ell_planes_from_csr(sub.indptr, sub.indices, sub.data,
                                   ri.size, pad_k=Kg)
        rows[p, : ri.size] = ri
        data[p, : ri.size] = d.astype(npdtype)
        cols[p, : ri.size] = c
    return StackedGhostBlock(rows=rows, data=data, cols=cols,
                             nrows=nmax_owned, bmax=bmax)


@dataclasses.dataclass
class DistributedProblem:
    """Host-side compilation of a partitioned matrix into mesh-ready arrays.

    The role of ``acgsolvercuda_init`` (``cgcuda.c:143-332``): upload the
    local + off-diagonal blocks and the halo plan, sized for the mesh.
    """

    nparts: int
    n: int
    subs: list[Subdomain]
    nmax_owned: int
    halo: DeviceHaloPlan
    local: StackedLocalBlock
    ghost: StackedGhostBlock
    nnz_total: int
    dtype: object
    # vector storage dtype; None = same as the matrix blocks.  The
    # supported split is bf16 blocks + f32 vectors ("--dtype mixed",
    # jax_cg.JaxCGSolver.vector_dtype rationale)
    vector_dtype: object = None

    @property
    def vdtype(self):
        return self.dtype if self.vector_dtype is None else self.vector_dtype

    # the matrix-free stencil operator armed over this problem
    # (arm_matfree; None = assembled local blocks).  The halo plan and
    # ghost block stay assembled either way -- the operator replaces
    # only the O(ndiags * N) local-plane HBM traffic
    operator: object = None

    # parts whose matrix blocks this controller built (None = all);
    # scatter() only fills these, matching the device shards this
    # process can address
    owned_parts: tuple | None = None
    # contiguous band boundaries (nparts+1) in the local-read flow:
    # lets gather()/scatter() use analytic global ids where non-owned
    # parts are stubs without them
    band_bounds: tuple | None = None

    @classmethod
    def build(cls, full_csr, part, nparts: int, dtype=jnp.float32,
              subs: list[Subdomain] | None = None,
              reorder: str = "natural",
              vector_dtype=None,
              owned_parts=None) -> "DistributedProblem":
        """``reorder="natural"`` (default) re-sorts each part's owned rows
        by global id (in place when ``subs`` is passed) so contiguous
        partitions of banded matrices keep gather-free DIA local blocks;
        ``"ibg"`` preserves the interior|border|ghost layout.

        ``owned_parts`` (multi-controller): assemble matrix blocks and
        host arrays only for the listed parts -- the rest stay as
        untouched calloc pages, so per-controller host RSS for the
        stacked problem is O(N * owned/nparts) instead of O(N).  Shape
        and format decisions then derive from the GLOBAL matrix so every
        controller compiles the identical program."""
        restricted = owned_parts is not None
        if subs is None or (not restricted and subs[0].A_local is None):
            subs = partition_matrix(full_csr, part, nparts,
                                    owned_parts=owned_parts)
        if reorder == "natural":
            reorder_owned_natural(subs)
        nmax_owned = max(s.nowned for s in subs)
        halo = build_device_halo(subs)
        gcsr = full_csr if restricted else None
        local = _stack_local_blocks(subs, nmax_owned, dtype, global_csr=gcsr)
        ghost = _stack_ghost_blocks(subs, nmax_owned, dtype, global_csr=gcsr)
        return cls(nparts=nparts, n=full_csr.shape[0], subs=subs,
                   nmax_owned=nmax_owned, halo=halo, local=local,
                   ghost=ghost, nnz_total=int(full_csr.nnz), dtype=dtype,
                   vector_dtype=vector_dtype,
                   owned_parts=None if owned_parts is None
                   else tuple(int(p) for p in owned_parts))

    @staticmethod
    def read_local_subdomains(path, nparts: int, mesh=None, bounds=None):
        """Phase 1 of the local-read flow: the HOST-LOCAL part (header
        read, per-part range reads, subdomain construction) with NO
        collectives -- so a one-sided I/O failure can be error-agreed at
        a checkpoint before any controller enters the shape allgather of
        :meth:`assemble_local` (mismatched collectives would otherwise
        cross-match and hang).  Returns ``(subs, bounds, n, owned)``."""
        from acg_tpu.errors import AcgError, ErrorCode
        from acg_tpu.graph import BandStub, subdomain_from_row_slice
        from acg_tpu.io.mtxfile import read_mtx_row_range, read_mtx_sizes

        n, _, _ = read_mtx_sizes(path)
        if bounds is None:
            bounds = np.linspace(0, n, nparts + 1).astype(np.int64)
        bounds = np.asarray(bounds, dtype=np.int64)
        if mesh is None:
            mesh = solve_mesh(nparts)
        pi = jax.process_index()
        owned = tuple(p for p in range(nparts)
                      if mesh.devices.flat[p].process_index == pi)
        subs: list = [None] * nparts
        for p in range(nparts):
            if p in owned:
                sl = read_mtx_row_range(path, int(bounds[p]),
                                        int(bounds[p + 1]))
                if sl.symmetry != "general":
                    raise AcgError(
                        ErrorCode.NOT_SUPPORTED,
                        f"{path}: range reads need FULL storage "
                        f"(symmetry 'general'); this file declares "
                        f"{sl.symmetry!r} -- regenerate with "
                        f"mtx2bin --expand")
                r, c, v = sl.to_coo()
                subs[p] = subdomain_from_row_slice(r, c, v, bounds, p)
            else:
                subs[p] = BandStub(part=p,
                                   nowned_=int(bounds[p + 1] - bounds[p]))
        return subs, bounds, n, owned

    @classmethod
    def assemble_local(cls, subs, bounds, n: int, nparts: int,
                       owned, dtype=jnp.float32,
                       vector_dtype=None) -> "DistributedProblem":
        """Phase 2 of the local-read flow: the COLLECTIVE part (uniform-
        shape allgather) plus stacking.  Call only after all controllers
        passed phase 1 (checkpointed)."""
        bounds = np.asarray(bounds, dtype=np.int64)
        nmax_owned = int(np.max(np.diff(bounds)))
        uniform = _agree_uniform_shapes([subs[p] for p in owned], nparts,
                                        nmax_owned=nmax_owned)
        halo = build_device_halo(subs, maxcnt=uniform.maxcnt,
                                 nmax_ghost=uniform.nmax_ghost)
        local = _stack_local_blocks(subs, nmax_owned, dtype, uniform=uniform)
        ghost = _stack_ghost_blocks(subs, nmax_owned, dtype, uniform=uniform)
        prob = cls(nparts=nparts, n=n, subs=subs, nmax_owned=nmax_owned,
                   halo=halo, local=local, ghost=ghost,
                   nnz_total=uniform.nnz_total, dtype=dtype,
                   vector_dtype=vector_dtype, owned_parts=owned,
                   band_bounds=tuple(int(b) for b in bounds))
        prob.halo_send_total = uniform.halo_send_total
        return prob

    @classmethod
    def build_local_read(cls, path, nparts: int, dtype=jnp.float32,
                         vector_dtype=None, mesh=None,
                         bounds=None) -> "DistributedProblem":
        """Pod-scale ingest: each controller RANGE-READS only its own
        rows from a row-sorted full-storage binary file (``mtx2bin
        --expand`` output) and builds only its own subdomains -- no
        controller ever holds the full matrix, its COO triplets, or any
        other part's blocks.  The role of the reference's root-rank read
        + subgraph scatter (``graph.c:1529-1897``,
        ``mtxfile.h:997-1087``) with the root removed: I/O, host memory
        and preprocessing are all O(local nnz).

        Uses a contiguous band partition (``bounds`` or equal rows);
        mesh-uniform shapes come from one small allgather
        (:func:`_agree_uniform_shapes`).  Structural symmetry of the
        matrix is assumed (SPD inputs) -- it is what makes the halo
        send side locally derivable (``graph.subdomain_from_row_slice``).

        Multi-controller callers that want clean one-sided-failure
        semantics should run :meth:`read_local_subdomains`, checkpoint,
        then :meth:`assemble_local` (the CLI does).
        """
        subs, bounds, n, owned = cls.read_local_subdomains(
            path, nparts, mesh=mesh, bounds=bounds)
        return cls.assemble_local(subs, bounds, n, nparts, owned,
                                  dtype=dtype, vector_dtype=vector_dtype)

    # -- vector scatter/gather to the stacked padded layout ---------------

    def scatter(self, x_global: np.ndarray, dtype=None) -> np.ndarray:
        out = np.zeros((self.nparts, self.nmax_owned),
                       dtype=np.dtype(dtype if dtype is not None
                                      else self.vdtype))
        owned = (range(self.nparts) if self.owned_parts is None
                 else self.owned_parts)
        x_global = np.asarray(x_global)
        for p in owned:
            s = self.subs[p]
            out[p, : s.nowned] = x_global[s.global_ids[: s.nowned]]
        return out

    def neighbor_counts(self):
        """(send_counts, recv_counts), each (nparts, nparts) int32:
        ``send_counts[p, q]`` = entries p sends to q.  Gates the puts in
        the DMA transport (the reference's per-neighbour sendcounts,
        ``halo.h:72-186``).

        In the local-read flow only owned parts carry plans; their rows
        are filled from local info (recv side directly from the owned
        recv windows -- the transpose shortcut would need other
        controllers' send rows), and non-owned rows stay zero: each
        controller's device shards only ever read its own rows."""
        scnt = np.zeros((self.nparts, self.nparts), dtype=np.int32)
        rcnt = np.zeros((self.nparts, self.nparts), dtype=np.int32)
        for p, s in enumerate(self.subs):
            h = s.halo
            if h is None:
                continue
            for q, cnt in zip(h.send_parts, h.send_counts):
                scnt[p, int(q)] = int(cnt)
            for q, cnt in zip(h.recv_parts, h.recv_counts):
                rcnt[p, int(q)] = int(cnt)
        if self.owned_parts is None:
            # full-information build: keep the exact transpose (identical
            # to the recv fill, but bit-for-bit the historical behavior)
            rcnt = scnt.T.copy()
        return scnt, rcnt

    def part_rows(self) -> list:
        """Owned row count per part, in part order -- half of the
        snapshot repartition sidecar (acg_tpu.checkpoint)."""
        if self.band_bounds is not None:
            return [int(self.band_bounds[p + 1] - self.band_bounds[p])
                    for p in range(self.nparts)]
        return [int(s.nowned) for s in self.subs]

    def row_permutation(self) -> np.ndarray | None:
        """Concatenated global row ids in stacked slot order (part 0's
        owned rows, then part 1's, ...): the permutation half of the
        snapshot repartition sidecar.  None when this controller
        cannot derive it (restricted multi-controller builds whose
        non-owned parts are stubs without band bounds) -- snapshots
        then omit the sidecar and repartition resume refuses
        self-describingly."""
        if self.band_bounds is not None:
            return np.concatenate([
                np.arange(self.band_bounds[p], self.band_bounds[p + 1],
                          dtype=np.int64)
                for p in range(self.nparts)]) if self.nparts else \
                np.zeros(0, np.int64)
        if self.owned_parts is not None:
            return None
        return np.concatenate([
            np.asarray(s.global_ids[: s.nowned], dtype=np.int64)
            for s in self.subs]) if self.subs else np.zeros(0, np.int64)

    def gather(self, stacked: np.ndarray) -> np.ndarray:
        out = np.zeros(self.n, dtype=np.asarray(stacked).dtype)
        if self.band_bounds is not None:
            # analytic global ids: non-owned parts are stubs here
            for p in range(self.nparts):
                lo, hi = self.band_bounds[p], self.band_bounds[p + 1]
                out[lo:hi] = stacked[p, : hi - lo]
            return out
        for p, s in enumerate(self.subs):
            out[s.global_ids[: s.nowned]] = stacked[p, : s.nowned]
        return out


def make_dist_spmv(prob: "DistributedProblem", comm: str, interpret: bool,
                   kernels: str = "xla", axis: str = PARTS_AXIS,
                   fault=None):
    """Shard-level distributed SpMV: halo(x) || local SpMV, then
    off-diagonal SpMV -- call stack 3.2's overlap pattern
    (``cgcuda.c:855-899``), scheduled by XLA instead of streams.

    ``kernels="pallas*"`` runs the hand-written single-x-pass DIA kernel
    for the local block (the role of the reference's device SpMV inside
    ``solvempi``, ``cgcuda.c:871``); non-DIA local blocks and the small
    ghost block stay on the XLA path.

    Returns ``f(x_loc, la, ga, sidx, gsrc, gval, scnt, rcnt, k=None,
    pidx=None)`` for use inside ``shard_map`` (shared by the solve
    program and the per-op profiling tier).  ``fault`` (a static
    acg_tpu.faults.FaultSpec) arms in-loop injection: ``k`` is the
    iteration index and ``pidx`` the shard's part index, so a
    ``halo:*``/``spmv:*`` spec poisons exactly one part's payload at
    exactly one iteration -- callers that never pass ``k`` (setup SpMVs,
    the profiler) are injection-free."""
    halo = prob.halo
    local_block = prob.local
    ghost_block = prob.ghost
    use_pallas = kernels.startswith("pallas") and local_block.format == "dia"
    pallas_interpret = kernels.endswith("interpret")
    if use_pallas:
        from acg_tpu.ops.pallas_kernels import dia_spmv

    def dist_spmv(x_loc, la, ga, sidx, gsrc, gval, scnt, rcnt,
                  k=None, pidx=None):
        if use_pallas:
            y = dia_spmv(la, local_block.offsets, x_loc,
                         interpret=pallas_interpret)
        else:
            y = local_block.shard_mv(la, x_loc)
        if halo.has_ghosts:
            if comm == "dma":
                ghost = halo_exchange_dma(x_loc, sidx, gsrc, gval,
                                          scnt, rcnt,
                                          axis, interpret=interpret)
            else:
                ghost = halo_exchange(x_loc, sidx, gsrc, axis)
            if fault is not None and k is not None:
                ghost = fault.apply_halo(ghost, k, pidx)
            y = y + ghost_block.shard_mv(ga, ghost)
        if fault is not None and k is not None:
            y = fault.apply_spmv(y, k, pidx)
        return y

    return dist_spmv


def interior_border_split(prob: "DistributedProblem") -> np.ndarray:
    """``(nparts, imax)`` int32 interior row ids per part, ascending,
    padded with ``nmax_owned`` (dropped by the jit scatter's OOB mode).

    A row is *border* when it couples to ghost values (it has entries in
    the off-diagonal block -- exactly the coupled-row list the stacked
    ghost block stores, ``StackedGhostBlock.rows``); every other owned
    row is *interior* and its SpMV result needs nothing from the halo
    exchange.  This is the reference's L1 interior/border graph split
    (``graph.c``: the rows whose update can start before any neighbour
    data lands), recomputed here from the halo plans instead of METIS
    metadata so every partition method gets it."""
    nrows = prob.nmax_owned
    interiors = []
    for s in prob.subs:
        if s is None or getattr(s, "A_ghost", None) is None:
            raise AcgError(
                ErrorCode.NOT_SUPPORTED,
                "interior/border split needs the full-information "
                "build (restricted multi-controller builds hold other "
                "controllers' coupled-row lists as stubs)")
        mask = np.ones(s.nowned, dtype=bool)
        coupled = np.flatnonzero(np.diff(s.A_ghost.indptr))
        mask[coupled[coupled < s.nowned]] = False
        interiors.append(np.flatnonzero(mask).astype(np.int32))
    imax = max((r.size for r in interiors), default=0) or 1
    out = np.full((prob.nparts, imax), nrows, dtype=np.int32)
    for p, r in enumerate(interiors):
        out[p, : r.size] = r
    return out


def make_dist_spmv_overlapped(prob: "DistributedProblem", comm: str,
                              interpret: bool, axis: str = PARTS_AXIS):
    """Interior|border OVERLAPPED distributed SpMV -- the fused tier's
    twin of :func:`make_dist_spmv` (``kernels='fused'`` on the mesh).

    The reference's device-initiated solver starts its one-sided halo
    puts, runs the interior SpMV while they are in flight, then waits
    the receive signals and finishes the border rows
    (``cg-kernels-cuda.cu:713-899``).  Restated as a DEPENDENCY split
    for XLA's scheduler: the exchange is issued first and nothing
    depends on it until the border finish, so the interior rows' work
    (a per-row gather SpMV over the interior row list) is free to
    overlap the puts; the border rows' local contribution plus the
    ghost contribution land after the recv wait.  Per-row arithmetic is
    bit-identical to the unsplit SpMV (same per-row multiply-add order
    over the same plane/ELL-slot sequence), so the split program's
    trajectory equals the unsplit one exactly (pinned in
    tests/test_fused_dist.py).

    ``ga`` arrives EXTENDED by the split: ``(rows, data, cols,
    interior_rows)`` -- the coupled-row list doubles as the border set,
    and :meth:`DistCGSolver.device_args` appends the interior list
    (:func:`interior_border_split`) when the fused tier is armed.
    Supports the ``dia`` and ``ell`` stacked local formats (the two
    with a per-row gather form); ``binnedell`` is refused at solver
    setup.  No fault hook: the fused tier refuses armed injectors at
    solve time (its base program carries no breakdown flag), so the
    signature keeps the ``k``/``pidx`` slots for call compatibility
    and nothing else."""
    halo = prob.halo
    local_block = prob.local
    ghost_block = prob.ghost
    if local_block.format not in ("dia", "ell", "matfree"):
        raise ValueError(f"overlapped SpMV needs DIA, ELL or matrix-"
                         f"free local blocks (got "
                         f"{local_block.format!r})")
    nrows = local_block.nrows
    offs = local_block.offsets

    def local_rows_mv(la, x, rows):
        """The local block's SpMV restricted to ``rows`` (padding ids
        == nrows gather clamped garbage that the caller's scatter
        drops).  Bit-identical per row to ``shard_mv``: the DIA form
        accumulates plane products in the same plane order over the
        same padded-x values (:func:`acg_tpu.ops.spmv.dia_mv`), the ELL
        form is the same row-independent einsum reduction, and the
        matrix-free form runs the DIA accumulation over GENERATED
        plane values (the interior/border split applied to the stencil
        apply -- the same split PR 13 gave the assembled SpMV)."""
        adt = acc_dtype(x.dtype)
        if local_block.format in ("dia", "matfree"):
            planes = (local_block.gen_planes(la)
                      if local_block.format == "matfree" else la)
            L = max(0, -min(offs))
            R = max(0, max(offs))
            xp = jnp.pad(x, (L, R))
            acc = jnp.zeros(rows.shape, adt)
            for plane, off in zip(planes, offs):
                acc = acc + (plane[rows].astype(adt)
                             * xp[rows + (L + off)].astype(adt))
            return acc.astype(x.dtype)
        data, cols = la
        return jnp.einsum("bk,bk->b", data[rows], x[cols[rows]],
                          preferred_element_type=adt).astype(x.dtype)

    def dist_spmv(x_loc, la, ga, sidx, gsrc, gval, scnt, rcnt,
                  k=None, pidx=None):
        grows, gdata, gcols, irows = ga
        # 1. issue the halo exchange FIRST: nothing below depends on it
        #    until the border finish, so the scheduler can run the
        #    interior SpMV while the one-sided puts (comm='dma') or the
        #    all_to_all are in flight -- the reference's stream overlap
        #    (cgcuda.c:855-899) as a data-dependency statement
        ghost = None
        if halo.has_ghosts:
            if comm == "dma":
                ghost = halo_exchange_dma(x_loc, sidx, gsrc, gval,
                                          scnt, rcnt, axis,
                                          interpret=interpret)
            else:
                ghost = halo_exchange(x_loc, sidx, gsrc, axis)
        # 2. interior rows: zero ghost dependencies, free to overlap
        with jax.named_scope("spmv_interior"):
            y_int = local_rows_mv(la, x_loc, irows)
        # 3+4. border finish: the border rows' local contribution plus
        #      the ghost contribution (which waits the recv side)
        with jax.named_scope("spmv_border"):
            y_bor = local_rows_mv(la, x_loc, grows)
            y = jnp.zeros((nrows,), x_loc.dtype)
            y = y.at[irows].add(y_int, indices_are_sorted=True)
            y = y.at[grows].add(y_bor, indices_are_sorted=True)
            if ghost is not None:
                contrib = jnp.einsum(
                    "bk,bk->b", gdata, ghost[gcols],
                    preferred_element_type=acc_dtype(x_loc.dtype)
                ).astype(x_loc.dtype)
                y = y.at[grows].add(contrib, indices_are_sorted=True)
        return y

    return dist_spmv


def arm_matfree(prob: "DistributedProblem", op) -> "DistributedProblem":
    """Arm the matrix-free operator tier over a built distributed
    problem: replace the assembled LOCAL planes with a ``matfree``
    stacked block whose shard-level SpMV GENERATES the stencil values
    (ops.operator.stencil_planes over per-part ``(row0, nowned)`` and
    the operator's O(grid-side) tables), while the halo plan and the
    ghost block -- the O(border) boundary-strip coupling -- stay
    assembled and ride the existing exchange machinery (all_to_all or
    one-sided DMA) unchanged.  In-place on ``prob``; returns it.

    Needs the full-information build over a CONTIGUOUS natural-order
    band partition (each part's local rows are then a global row range,
    so the generated global planes masked to the owned window equal the
    assembled ``dia_planes_fixed`` stacking bitwise); anything else
    refuses self-describingly rather than silently answering a
    different system."""
    from acg_tpu.ops.operator import StencilOperator

    if not isinstance(op, StencilOperator):
        raise AcgError(
            ErrorCode.NOT_SUPPORTED,
            "the distributed matrix-free tier runs the built-in "
            "stencil operators (their local structure is derivable per "
            "part); user-registered operators ride the single-device "
            "tiers")
    if prob.owned_parts is not None:
        raise AcgError(
            ErrorCode.NOT_SUPPORTED,
            "matrix-free arming needs the full-information build "
            "(restricted multi-controller builds hold other "
            "controllers' subdomains as stubs)")
    if int(op.nrows) != int(prob.n):
        raise AcgError(
            ErrorCode.INVALID_VALUE,
            f"operator computes a {op.nrows}-row system; this problem "
            f"has {prob.n} rows")
    if np.dtype(str(op.dtype)) != np.dtype(prob.dtype):
        raise AcgError(
            ErrorCode.INVALID_VALUE,
            f"operator dtype {op.dtype} != problem dtype "
            f"{np.dtype(prob.dtype)}")
    rows0, nowns = [], []
    for s in prob.subs:
        gids = np.asarray(s.global_ids[: s.nowned], dtype=np.int64)
        if s.nowned and (s.owned_order != "natural"
                         or int(gids[-1]) - int(gids[0]) + 1 != s.nowned):
            raise AcgError(
                ErrorCode.NOT_SUPPORTED,
                f"matrix-free stencils need a contiguous natural-order "
                f"band partition (part {s.part} owns a scattered row "
                f"set); use --partition-method band")
        rows0.append(int(gids[0]) if s.nowned else 0)
        nowns.append(int(s.nowned))
    P = prob.nparts
    arrays = (np.asarray(rows0, np.int32).reshape(P, 1),
              np.asarray(nowns, np.int32).reshape(P, 1))
    for t in op.tables:
        arrays = arrays + (np.broadcast_to(
            np.asarray(t), (P,) + np.shape(t)).copy(),)
    prob.local = StackedLocalBlock(format="matfree", arrays=arrays,
                                   offsets=op.offsets,
                                   nrows=prob.nmax_owned, operator=op)
    prob.operator = op
    return prob


class DistCGSolver:
    """Whole-solve SPMD CG program over a 1-D mesh of ``nparts`` devices.

    ``comm`` selects the halo transport (the reference's ``--comm``
    choice, ``cuda/acg-cuda.c:321-377``): ``"xla"`` = `lax.all_to_all`
    collectives (the NCCL/MPI analog), ``"dma"`` = Pallas one-sided
    remote copies (the NVSHMEM analog, halo_dma.py).
    """

    def __init__(self, problem: DistributedProblem, pipelined: bool = False,
                 mesh: Mesh | None = None, comm: str = "xla",
                 precise_dots: bool = False, kernels: str = "auto",
                 replace_every: int = 0, replace_restart: bool = True,
                 recovery=None, trace: int = 0, progress: int = 0,
                 precond=None, health=None, ckpt=None, algorithm=None):
        """``recovery`` (acg_tpu.solvers.resilience.RecoveryPolicy) arms
        in-loop breakdown detection plus the host-side restart ladder:
        bounded restarts from the recomputed true residual, the
        dma -> xla halo-transport fallback, and (full single-controller
        builds) the distributed host solver -- with every restart/abort
        decision error-agreed across controllers.

        ``trace``/``progress`` (acg_tpu.telemetry, 0 = off) arm the
        in-loop convergence ring buffer / the heartbeat in the SPMD
        loop.  Every recorded scalar is already psum'd, so the buffer
        is replicated across shards and leaves the mesh as ONE
        rank-independent fetch per solve; the heartbeat fires on part 0
        only.

        ``precond`` (acg_tpu.precond: spec / spec string / None) arms
        PCG / pipelined-PCG over the mesh: Jacobi and block-Jacobi
        state comes from each part's LOCAL block (stacked host-side,
        sharded like the matrix -- zero extra communication per apply),
        Chebyshev's lambda_max from a power iteration compiled over the
        same halo'd SpMV the solve uses.  The classic loop keeps 2
        allreduces per iteration (the second fuses (r, z) with (r, r))
        and the pipelined loop keeps its SINGLE fused allreduce (3
        scalars).

        ``health`` (acg_tpu.health.HealthSpec or None) arms the
        numerical-health tier over the mesh: the in-loop audit
        recomputes ``b - A x`` through the SAME halo'd distributed
        SpMV the solve runs (inside a ``lax.cond`` whose predicate --
        the iteration index -- is identical on every shard, so the
        conditional collectives stay mesh-uniform), the gap psums, and
        the carried audit vector is replicated like the telemetry
        ring.  ``None`` compiles the byte-identical unaudited
        program."""
        if comm not in ("xla", "dma"):
            raise ValueError(f"unknown halo transport {comm!r}")
        # multi-controller comm='dma': a CAPABILITY PROBE (the conftest
        # two-process-probe pattern, library-side) decides whether the
        # one-sided transport can run in this topology; an incapable
        # topology DOWNGRADES to the xla collectives with a
        # self-describing event instead of the old hard refusal --
        # single-controller runs (where the transport is proven:
        # scripts/dma_probe.py on silicon, interpret-mode parity in CI)
        # pass through without any stale validation caveat
        self._comm_downgrade = None
        if comm == "dma" and jax.process_count() > 1:
            from acg_tpu.parallel.halo_dma import dma_transport_status
            ok, why = dma_transport_status()
            if not ok:
                comm = "xla"
                self._comm_downgrade = why
                sys.stderr.write(
                    f"acg-tpu: halo transport dma -> xla: {why}\n")
        self.problem = problem
        self.pipelined = pipelined
        self.precise_dots = precise_dots
        self.comm = comm
        # recurrence selection (acg_tpu.recurrence): classic/pipelined
        # stay on the hand-built shard_body (builder emission pinned
        # byte-identical in tests/test_hlo_structure.py); sstep:S /
        # pipelined:L compose the builder recurrences with this tier's
        # halo'd SpMV + fused psum machinery (_compile_ca)
        from acg_tpu.recurrence import parse_algorithm
        self.algo = parse_algorithm(algorithm)
        if self.algo is not None and not self.algo.communication_avoiding:
            self.pipelined = pipelined = (self.algo.kind == "pipelined")
            self.algo = None
        self._lam = None
        self.mesh = mesh if mesh is not None else solve_mesh(problem.nparts)
        self.stats = SolverStats(unknowns=problem.n)
        self._sharding = NamedSharding(self.mesh, P(PARTS_AXIS))
        self._interpret = self.mesh.devices.flat[0].platform != "tpu"
        # kernel-tier resolution mirrors JaxCGSolver: pallas on TPU
        # hardware for f32/bf16 DIA local blocks, interpret mode when
        # explicitly requested off-TPU (tests), XLA otherwise
        itemsize = np.dtype(problem.dtype).itemsize
        if kernels == "auto":
            kernels = ("pallas" if not self._interpret
                       and itemsize in (2, 4)
                       and problem.local.format == "dia" else "xla")
        elif kernels == "pallas" and self._interpret:
            kernels = "pallas-interpret"
        elif kernels.startswith("fused"):
            # the distributed fused-iteration tier (ROADMAP item 4):
            # builder-emitted classic/pipelined recurrences over the
            # interior|border split SpMV with the halo exchange in
            # flight (make_dist_spmv_overlapped).  Needs a per-row
            # gather form of the local block and the full-information
            # build (the split derives from every part's coupled-row
            # list)
            if problem.local.format not in ("dia", "ell", "matfree"):
                raise ValueError(
                    "kernels='fused' needs DIA, ELL or matrix-free "
                    f"local blocks (this problem stacked "
                    f"{problem.local.format!r}, which has no per-row "
                    f"gather form); use kernels='auto'")
            if problem.owned_parts is not None:
                raise ValueError(
                    "kernels='fused' needs the full-information build: "
                    "restricted multi-controller builds hold other "
                    "controllers' coupled-row lists as stubs, so the "
                    "interior/border split is not derivable")
            kernels = "fused"
        if kernels not in ("xla", "pallas", "pallas-interpret", "fused"):
            raise ValueError(f"unknown kernels choice {kernels!r}")
        self.kernels = kernels
        self.replace_every = int(replace_every)
        self.replace_restart = bool(replace_restart)
        if self.replace_every < 0:
            raise ValueError("replace_every must be >= 0")
        if self.replace_every:
            # same contract as the single-device solver (jax_cg): the
            # bf16 tier's periodic-f32-residual-replacement soundness
            # mechanism, distributed
            if np.dtype(problem.vdtype) != np.dtype(jnp.bfloat16):
                raise ValueError(
                    "replace_every is the bf16 tier's accuracy contract; "
                    "build the problem with vector_dtype=bf16 (f32/f64 "
                    "storage has no replacement drift to correct)")
            if pipelined:
                raise ValueError("replace_every implements classic CG")
            if precise_dots:
                raise ValueError("replace_every computes scalars in "
                                 "plain f32; precise_dots needs the "
                                 "direct programs")
        from acg_tpu.precond import parse_precond
        self.precond_spec = parse_precond(precond)
        if self.precond_spec is not None and self.replace_every:
            raise ValueError(
                "precond does not compose with replace_every: the "
                "replacement segments restructure the recurrences the "
                "preconditioner threads through")
        # preconditioner state: host-stacked (jacobi/bjacobi) or device
        # scalars (cheby), built lazily at first solve/lower
        self._mstate = None
        # numerical-health tier (acg_tpu.health): static spec baked
        # into the compiled SPMD program; refusals mirror JaxCGSolver's
        if health is not None:
            from acg_tpu.health import HealthSpec
            if not isinstance(health, HealthSpec):
                raise ValueError("health must be an "
                                 "acg_tpu.health.HealthSpec or None")
            if not health.armed:
                health = None
        if health is not None and self.replace_every:
            raise ValueError(
                "the true-residual audit (health) does not compose "
                "with replace_every: the replacement segments already "
                "recompute b - A x every K iterations")
        self.health_spec = health
        # survivability tier (acg_tpu.checkpoint): an armed
        # CheckpointConfig turns solve() into the host-chunked snapshot
        # driver (the JaxCGSolver discipline; same refusals)
        if ckpt is not None:
            from acg_tpu.checkpoint import CheckpointConfig
            if not isinstance(ckpt, CheckpointConfig):
                raise ValueError("ckpt must be an acg_tpu.checkpoint."
                                 "CheckpointConfig or None")
            if self.replace_every:
                raise ValueError(
                    "checkpointing (ckpt) does not compose with "
                    "replace_every: the replacement segments' inner "
                    "state never leaves the program (use the direct "
                    "classic/pipelined programs)")
        self.ckpt = ckpt
        if self.algo is not None:
            # the CA refusal set mirrors JaxCGSolver's (the
            # could-never-fire discipline)
            ca = str(self.algo)
            if pipelined:
                raise ValueError(
                    f"--algorithm {ca} selects its own recurrence; it "
                    f"does not compose with the pipelined flag")
            if self.replace_every:
                raise ValueError(
                    f"{ca} does not compose with replace_every")
            if self.precise_dots:
                raise ValueError(
                    f"{ca} accumulates its fused Gram/window reductions "
                    f"in the scalar dtype; precise_dots composes with "
                    f"the classic/pipelined programs")
            if self.precond_spec is not None:
                raise ValueError(
                    f"{ca} runs unpreconditioned: the s-step basis and "
                    f"the p(l) auxiliary basis have no M^-1 hook yet")
            if np.dtype(problem.vdtype) == np.dtype(jnp.bfloat16):
                raise ValueError(
                    f"{ca} amplifies storage rounding through its basis "
                    f"products; bf16 vectors need the classic/pipelined "
                    f"tiers")
            if ckpt is not None:
                raise ValueError(
                    f"{ca} checkpoints on the single-device tier only "
                    f"(checkpoint.ca_carry_names); on the mesh, "
                    f"--ckpt/--resume need --algorithm "
                    f"classic|pipelined")
            if self.health_spec is not None:
                if self.algo.kind == "pl":
                    raise ValueError(
                        f"{ca} has no in-loop audit hook; --audit-every "
                        f"needs classic/pipelined/sstep")
                if self.health_spec.abft:
                    raise ValueError(
                        f"{ca} has no checksum hook for its basis "
                        f"products; --abft needs classic/pipelined")
        if (self.algo is not None and self.algo.kind == "pl"
                and recovery is None):
            # restarted p(l)-CG (the jax_cg rationale): sqrt breakdown
            # is algorithmic; arm the restart ladder by default
            from acg_tpu.recurrence import pl_restart_policy
            recovery = pl_restart_policy()
        self.recovery = recovery
        self.trace = int(trace)
        self.progress = int(progress)
        if self.trace < 0 or self.progress < 0:
            raise ValueError("trace/progress must be >= 0 (iteration "
                             "counts; 0 disables)")
        if self.replace_every and (self.trace or self.progress):
            # the replacement segments' inner fori threads no global
            # iteration index: the telemetry hooks would silently
            # record nothing (the fault-injector refusal rationale)
            raise ValueError(
                "convergence telemetry (trace/progress) does not reach "
                "the replacement-segment program (replace_every); use "
                "the direct classic/pipelined programs")
        self.last_trace = None
        if self.kernels == "fused":
            # the fused tier dispatches the BUILDER base program
            # (recurrence.build_dist_program over the overlapped SpMV):
            # every cross-cutting feature it does not thread refuses
            # here rather than silently dropping (the could-never-fire
            # discipline, mirroring the single-device fused tier)
            for on, what in (
                    (self.replace_every,
                     "replace_every (the replacement segments "
                     "restructure the loop)"),
                    (self.precise_dots,
                     "precise_dots (the fused tier accumulates its "
                     "dots in the plain scalar dtype)"),
                    (self.precond_spec is not None,
                     "precond (no preconditioner hook in the fused "
                     "base program)"),
                    (self.health_spec is not None,
                     "the health audit (no audit hook in the fused "
                     "base program)"),
                    (self.ckpt is not None,
                     "checkpointing (the fused base program exposes "
                     "no loop carry)"),
                    (self.algo is not None,
                     f"--algorithm {self.algo} (the CA recurrences "
                     f"keep the unsplit SpMV; fused covers "
                     f"classic/pipelined)"),
                    (self.recovery is not None,
                     "recovery (the fused base program carries no "
                     "breakdown flag, so a policy could never fire)"),
                    (bool(self.trace or self.progress),
                     "convergence telemetry (trace/progress)")):
                if on:
                    raise ValueError(
                        f"kernels='fused' (dist) does not compose with "
                        f"{what}; use kernels='auto'/'xla'/'pallas'")
        self._program = self._compile()

    def _program_for(self, fault):
        """The solve program matching the current comm + fault state:
        armed faults always get a solve-local compile; the pristine
        program is cached (and lazily rebuilt after a transport
        fallback invalidates it)."""
        if fault is not None:
            return self._compile(fault=fault)
        if self._program is None:
            self._program = self._compile()
        return self._program

    # -- program construction ---------------------------------------------

    def _compile(self, fault=None, state_io: bool = False):
        """Build the whole-solve program.  ``fault`` (a static
        acg_tpu.faults.FaultSpec) bakes the injector into the loop --
        the armed program is a solve-local temporary, never cached on
        ``self``, so clean solves keep the pristine compilation.

        ``state_io`` (the survivability tier, acg_tpu.checkpoint) makes
        the program ALSO return the final loop carry -- per-part vector
        leaves sharded like x, psum'd scalars replicated -- and accept
        an optional ``carry``/``k_offset`` pair that re-enters the
        recurrence exactly where a previous chunk left it (the
        checkpoint chunk driver's plumbing).  Disarmed programs never
        name any of it and lower byte-identical code (pinned in
        tests/test_checkpoint.py)."""
        if self.algo is not None:
            # communication-avoiding recurrences: the builder program
            # (recurrence.run_sstep_loop / run_pl_loop) composed with
            # this tier's machinery
            return self._compile_ca(fault=fault)
        if self.kernels == "fused":
            # the distributed fused-iteration tier: the recurrence
            # builder's base emission (classic_recurrence /
            # pipelined_recurrence over TierOps) composed with the
            # interior|border OVERLAPPED SpMV -- no hand-built loop
            # (the PR 12 one-recurrence-per-feature discipline).
            # Faults/state_io never reach here: both are refused at
            # setup/solve for this tier
            from acg_tpu.recurrence import build_dist_program
            return build_dist_program(self)
        prob = self.problem
        pipelined = self.pipelined
        replace_every = self.replace_every
        replace_restart = self.replace_restart
        axis = PARTS_AXIS

        comm = self.comm
        interpret = self._interpret
        precise = self.precise_dots
        trace = self.trace
        progress = self.progress
        precond_spec = self.precond_spec
        health = self.health_spec
        if trace or progress:
            from acg_tpu import telemetry
        if precond_spec is not None:
            from acg_tpu.precond import make_apply
        if health is not None:
            from acg_tpu import health as _health

        dist_spmv = make_dist_spmv(prob, comm, interpret,
                                   kernels=self.kernels, fault=fault)

        # commsize==1 parity (the reference's explicit special case,
        # ``cgcuda.c:403``): on a 1-shard mesh every psum is an identity
        # -- but XLA does NOT elide a 1-device all-reduce, and on this
        # runtime each one costs a fixed per-op launch overhead INSIDE
        # the iteration loop (measured round 5: 2 all-reduces/iteration
        # made the nparts=1 program 27x slower than the single-chip
        # solver, the LADDER_r04 `cg_dist1` collapse).  The whole
        # shard_map wrapper is bypassed below for the same reason.
        single_shard = self.mesh.devices.size == 1

        def psum(v):
            return v if single_shard else lax.psum(v, axis)

        # the loop-carry leaf layout a snapshot stores (acg_tpu.
        # checkpoint): vector leaves shard per-part, the psum'd scalars
        # replicate -- shared by shard_body's state_io outputs, the
        # shard_map specs, and the chunk driver's snapshot writer
        from acg_tpu.checkpoint import SCALAR_LEAVES, carry_names
        c_names = carry_names(pipelined, precond_spec is not None)[1:]
        # the GLOBAL unknown count (the ABFT mismatch scale; local
        # shapes would understate the rounding headroom)
        nglobal = int(prob.n)

        def shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                       tols, maxits, mstate=None, unbounded=False,
                       needs_diff=False, detect=False, carry=None,
                       k_offset=None):
            # shard_map keeps the sharded parts axis as a leading size-1 dim
            la, ga = (jax.tree.map(lambda a: a[0], t) for t in (la, ga))
            sidx, gsrc, gval, scnt, rcnt, b, x0 = (
                a[0] for a in (sidx, gsrc, gval, scnt, rcnt, b, x0))
            if precond_spec is not None:
                mstate = jax.tree.map(lambda a: a[0], mstate)
            if carry is not None:
                # vector leaves arrive stacked like b; psum'd scalars
                # arrive replicated (shape ()) and pass through
                carry = tuple(a[0] if a.ndim == 2 else a for a in carry)
            maxits = maxits.astype(jnp.int32)
            dtype = b.dtype
            # bf16 storage keeps every scalar in f32 (jax_cg._scalar_setup
            # rationale): dots accumulate in f32, updated vectors round
            # once on store, only half-width bytes cross HBM and the ICI
            sdt = acc_dtype(dtype)
            store = ((lambda v: v.astype(dtype)) if sdt != dtype
                     else (lambda v: v))
            res_atol, res_rtol, diff_atol, diff_rtol = tols
            # the part index a vector fault targets; only derivable from
            # the mesh axis inside shard_map (the plain-jit bypass below
            # is single-part by construction)
            pidx = None
            if fault is not None:
                pidx = (jnp.int32(0) if single_shard
                        else lax.axis_index(axis))

            def spmv(x, k=None):
                return dist_spmv(x, la, ga, sidx, gsrc, gval, scnt, rcnt,
                                 k=k, pidx=pidx)

            def ldot(a, c):
                return jnp.dot(a, c, preferred_element_type=sdt)

            # the fused-reduction family (parallel.reductions): ONE
            # psum carries k scalars -- compensated mode psums hi/lo
            # pairs so local summation error stays out of the global
            # scalar, and the pipelined/PCG single-allreduce property
            # (cgcuda.c:1730-1737) is the k=2/k=3 member.  The builders
            # emit exactly the op sequence the hand-written ladders
            # traced, so these programs lower byte-identically to the
            # pre-refactor ones (pinned in tests/test_hlo_structure.py)
            pdot = make_pdot(psum, ldot, sdt, precise)
            _pdotk = make_pdotk(psum, ldot, sdt, precise)

            def pdot2_fused(a1, c1, a2, c2):
                return _pdotk((a1, c1), (a2, c2))

            def pdot3_fused(a1, c1, a2, c2, a3, c3):
                return _pdotk((a1, c1), (a2, c2), (a3, c3))

            bnrm2 = jnp.sqrt(pdot(b, b))
            x0nrm2 = jnp.sqrt(pdot(x0, x0))
            if precond_spec is not None:
                # papply reuses the tier's halo'd SpMV closure: the
                # cheby apply's communication is exactly K extra SpMVs
                _papply = make_apply(precond_spec, lambda _A, x: spmv(x))

                def papply(vec, k=None):
                    z = _papply(mstate, None, vec)
                    if fault is not None and k is not None:
                        z = fault.apply_precond(z, k, pidx)
                    return z

            if carry is not None:
                # resume (the survivability tier): the provided carry IS
                # the loop state -- nothing is recomputed, the Krylov
                # recurrence continues exactly where the snapshot left
                # it (x0 holds the snapshot iterate).  The setup SpMV
                # and its collectives are skipped on every shard alike
                # (carry is a static python branch, mesh-uniform)
                r = carry[0]
                if precond_spec is not None:
                    r0nrm2 = jnp.sqrt(carry[-1])
                elif pipelined:
                    r0nrm2 = jnp.sqrt(jnp.maximum(carry[-2], 0))
                else:
                    r0nrm2 = jnp.sqrt(carry[-1])
            elif precond_spec is not None:
                r = b - spmv(x0)
                u0 = store(papply(r))
                gamma0, rr0 = pdot2_fused(r, u0, r, r)
                gamma = rr0
                r0nrm2 = jnp.sqrt(rr0)
            else:
                r = b - spmv(x0)
                gamma = pdot(r, r)
                r0nrm2 = jnp.sqrt(gamma)
            res_tol = jnp.maximum(res_atol, res_rtol * r0nrm2)
            diff_tol = jnp.maximum(diff_atol, diff_rtol * x0nrm2)
            inf = jnp.asarray(jnp.inf, sdt)
            if health is not None and health.abft:
                # the column checksum c = A^T 1 (= A 1: symmetric
                # systems) through the tier's own halo'd SpMV -- one
                # extra exchange per solve.  The in-loop test rides the
                # FUSED 3-dot psum (pdot3_fused), so the armed delta is
                # exactly +1 all_reduce per audit and ZERO extra SpMVs
                cvec = spmv(jnp.ones_like(b)).astype(sdt)

            # Loop structure and convergence logic shared with the
            # single-device solver (jax_cg._iterate / _converged): gamma is
            # psum'd, so `done` is identical on every shard and the while
            # predicates agree across the mesh.
            def run_iter(iter_body, init_state, gamma_of, dx_of,
                         init_gamma=None, bad_of=None):
                return _iterate(iter_body, init_state, gamma_of, maxits,
                                res_tol, diff_tol, dx_of, unbounded,
                                init_gamma=init_gamma, bad_of=bad_of)

            if replace_every and not pipelined:
                # the sound-bf16 contract, distributed: inner bf16 CG
                # segments over the mesh with a per-segment f32
                # true-residual replacement (mixed-precision dist SpMV
                # -- bf16 blocks x f32 vector).  Mirrors
                # jax_cg._cg_replaced_program; b/x0 arrive in f32
                # (solve scatters them wide), and every psum'd scalar
                # is f32, so the convergence test per segment is
                # grounded in the true residual on every shard.
                vdt = jnp.bfloat16

                def segment(x32, r32, p, its):
                    r16 = r32.astype(vdt)
                    seg_gamma = pdot(r16, r16)
                    if replace_restart:
                        p = r16
                    else:
                        pn = pdot(p, p)
                        bad = ((~jnp.isfinite(pn))
                               | (pn > jnp.asarray(1e24, sdt) * seg_gamma))
                        p = jnp.where(bad, r16, p)
                    nin = jnp.minimum(jnp.int32(replace_every), maxits - its)

                    def ibody(j, st):
                        d, rr, pp, g = st
                        live = j < nin
                        t = spmv(pp)
                        pdott = pdot(pp, t)
                        num = g if replace_restart else pdot(rr, pp)
                        alpha = jnp.where(live & (pdott > 0), num / pdott,
                                          jnp.zeros_like(g))
                        d = (d.astype(sdt)
                             + alpha * pp.astype(sdt)).astype(vdt)
                        r_new = (rr.astype(sdt)
                                 - alpha * t.astype(sdt)).astype(vdt)
                        g_next = pdot(r_new, r_new)
                        beta = jnp.where(g > 0, g_next / g,
                                         jnp.zeros_like(g))
                        pp = jnp.where(live,
                                       (r_new.astype(sdt)
                                        + beta * pp.astype(sdt)).astype(vdt),
                                       pp)
                        return (d, r_new, pp, g_next)

                    d, _, p, _ = jax.lax.fori_loop(
                        0, replace_every, ibody,
                        (jnp.zeros_like(r16), r16, p, seg_gamma))
                    x32 = x32 + d.astype(sdt)
                    r32 = b - spmv(x32)
                    return x32, r32, p, its + nin, pdot(r32, r32)

                p0 = r.astype(vdt)
                if unbounded:
                    nouter = ((maxits + jnp.int32(replace_every) - 1)
                              // jnp.int32(replace_every))

                    def obody(_, carry):
                        x32, r32, p, its, _ = carry
                        return segment(x32, r32, p, its)

                    x32, _, _, k, gamma_f = jax.lax.fori_loop(
                        0, nouter, obody,
                        (x0, r, p0, jnp.int32(0), gamma))
                    done = jnp.isfinite(gamma_f)
                else:
                    def wcond(c):
                        # NaN >= x is False: a non-finite recomputed
                        # residual exits here -- the segment boundary
                        # doubles as the breakdown detector for free
                        return (c[4] >= res_tol * res_tol) & (c[3] < maxits)

                    def wbody(c):
                        return segment(*c[:4])

                    x32, _, _, k, gamma_f = jax.lax.while_loop(
                        wcond, wbody, (x0, r, p0, jnp.int32(0), gamma))
                    done = gamma_f < res_tol * res_tol
                return (x32[None], k, jnp.sqrt(gamma_f), r0nrm2, bnrm2,
                        x0nrm2, inf, done, ~jnp.isfinite(gamma_f))

            # heartbeat emits from part 0 only: every recorded scalar is
            # psum'd (mesh-uniform), so one part speaks for the mesh
            leader = None
            if progress and not single_shard:
                leader = lax.axis_index(axis) == jnp.int32(0)

            if not pipelined:
                # carry layout mirrors jax_cg._cg_program: rr (the true
                # residual the convergence test reads) joins only under
                # precond, dx only under a diff criterion
                dx_i = 5 if precond_spec is not None else 4

                # dxsqr joins the carry only under a diff criterion (extra
                # loop-carried scalars measurably slow the TPU loop)
                def body(k, state):
                    if trace:
                        buf, state = state[-1], state[:-1]
                    if health is not None:
                        aud, state = state[-1], state[:-1]
                    x, r, p, gamma = state[:4]
                    t = spmv(p, k)
                    pdott = pdot(p, t)
                    if fault is not None:
                        pdott = fault.apply_dot(pdott, k)
                    if detect:
                        # breakdown detection mirrors jax_cg._cg_program
                        # (shared predicate; the deferred gamma_next
                        # term below too): every flagged scalar is
                        # psum'd, so `bad` is identical on all shards
                        # and the early exit is mesh-uniform
                        bad, alpha = _breakdown_guard(gamma, pdott)
                        x = store(jnp.where(bad, x, x + alpha * p))
                        r = store(jnp.where(bad, r, r - alpha * t))
                    else:
                        alpha = gamma / pdott
                        x = store(x + alpha * p)
                        r = store(r - alpha * t)
                    if precond_spec is not None:
                        z = papply(r, k)
                        # ONE fused psum for both scalars: the classic
                        # PCG loop keeps 2 allreduces per iteration
                        gamma_next, rr_next = pdot2_fused(r, z, r, r)
                        beta = gamma_next / gamma
                        p_next = store(z + beta * p)
                        out = (x, r, p_next, gamma_next, rr_next)
                    else:
                        gamma_next = pdot(r, r)
                        beta = gamma_next / gamma
                        p_next = store(r + beta * p)
                        out = (x, r, p_next, gamma_next)
                    if needs_diff:
                        dx = alpha * alpha * psum(ldot(p, p))
                        if detect:
                            # freeze dx on breakdown (jax_cg rationale):
                            # alpha = 0 must not fake the diff criterion
                            dx = jnp.where(bad, state[dx_i], dx)
                        out = out + (dx,)
                    fire = None
                    if health is not None:
                        # cadence phased to TRAJECTORY iterations: the
                        # checkpoint chunk driver passes the chunk's
                        # starting iteration (mesh-uniform, like k)
                        kk = k if k_offset is None else k + k_offset

                        # in-loop audit through the SAME halo'd SpMV:
                        # the cond predicate (k) is mesh-uniform, so
                        # the conditional collectives fire on every
                        # shard together; the psum'd gap replicates
                        def compute_gap():
                            return _health.relative_gap(b - spmv(x), r,
                                                                                pdot, bnrm2, sdt)

                        aud, fire = _health.audit_update(
                            aud, health, kk, compute_gap)
                        prog_now = (out[4] if precond_spec is not None
                                    else gamma_next)
                        prog_prev = (state[4] if precond_spec is not None
                                     else gamma)
                        aud = _health.stall_update(aud, health,
                                                   prog_now < prog_prev)
                        if health.abft:
                            # Huang-Abraham checksum test of this
                            # iteration's t = A p: sum(t) vs (c, p),
                            # all three scalars in ONE fused psum
                            aud = _health.abft_update(
                                aud, health, kk, t, p, cvec,
                                pdot3_fused, sdt, nglobal)
                    if detect:
                        deferred = bad | (~jnp.isfinite(gamma_next))
                        if precond_spec is not None:
                            # negative (r, z): the non-SPD-M signal
                            deferred = deferred | (gamma_next < 0)
                        if health is not None:
                            if precond_spec is None:
                                # sign anomaly (jax_cg rationale)
                                deferred = deferred | (gamma_next < 0)
                            deferred = deferred | _health.trip(aud,
                                                               health)
                        out = out + (deferred,)
                    if health is not None:
                        out = out + (aud,)
                    if trace:
                        # psum'd scalars: the ring is replicated, one
                        # rank-independent fetch per solve (gamma IS the
                        # preconditioned residual norm^2 under precond)
                        audit_col = (_health.ring_gap(aud, fire, sdt)
                                     if health is not None else None)
                        out = out + (telemetry.ring_record(
                            buf, k, gamma_next, alpha, beta, pdott,
                            audit=audit_col),)
                    if progress:
                        telemetry.heartbeat(k, gamma_next, progress,
                                            leader=leader, what="dist-cg")
                    return out

                if carry is not None:
                    init_state = (x0,) + tuple(carry)
                elif precond_spec is not None:
                    init_state = (x0, r, u0, gamma0, rr0)
                else:
                    init_state = (x0, r, r, gamma)
                init_state = init_state + ((inf,) if needs_diff else ())
                if detect:
                    init_state = init_state + (jnp.asarray(False),)
                if health is not None:
                    init_state = init_state + (_health.audit_init(sdt,
                                                                  health),)
                if trace:
                    init_state = init_state + (telemetry.ring_init(
                        trace, sdt, audit=health is not None),)
                bad_i = -1 - (1 if trace else 0) - (
                    1 if health is not None else 0)
                conv_i = 4 if precond_spec is not None else 3
                k, state, done = run_iter(
                    body, init_state, lambda s: s[conv_i],
                    (lambda s: s[dx_i]) if needs_diff else (lambda s: inf),
                    bad_of=(lambda s: s[bad_i]) if detect else None)
                x, r_fin, gamma_fin = state[0], state[1], state[conv_i]
                dxsqr = state[dx_i] if needs_diff else inf
                breakdown = state[bad_i] if detect else jnp.asarray(False)
                tbuf = state[-1] if trace else None
                aud_out = (state[-2] if trace else state[-1]) \
                    if health is not None else None
                rnrm2 = jnp.sqrt(gamma_fin)
            elif precond_spec is not None:
                # preconditioned Ghysels-Vanroose (jax_cg pbody, psum'd):
                # ONE fused 3-scalar allreduce per iteration, the
                # preconditioner apply + its SpMV overlapping it
                if carry is None:
                    w = spmv(u0)
                zeros = jnp.zeros_like(b)

                def pbody(k, state):
                    if trace:
                        buf, state = state[-1], state[:-1]
                    if health is not None:
                        aud, state = state[-1], state[:-1]
                    x, r, u, w, p, s, q, z, gamma_prev, alpha_prev = \
                        state[:10]
                    rr_prev = state[10]
                    gamma, delta, rr = pdot3_fused(r, u, w, u, r, r)
                    if fault is not None:
                        delta = fault.apply_dot(delta, k)
                    m = papply(w, k)
                    nvec = spmv(m, k)
                    beta = gamma / gamma_prev
                    denom = delta - beta * (gamma / alpha_prev)
                    if detect:
                        bad, alpha = _breakdown_guard(gamma, denom)
                        bad = bad | (gamma < 0)
                        alpha = jnp.where(bad, jnp.zeros_like(alpha),
                                          alpha)
                    else:
                        alpha = gamma / denom
                    z = store(nvec + beta * z)
                    q = store(m + beta * q)
                    s = store(w + beta * s)
                    p = store(u + beta * p)
                    if detect:
                        x = store(jnp.where(bad, x, x + alpha * p))
                        r = store(jnp.where(bad, r, r - alpha * s))
                        u = store(jnp.where(bad, u, u - alpha * q))
                        w = store(jnp.where(bad, w, w - alpha * z))
                    else:
                        x = store(x + alpha * p)
                        r = store(r - alpha * s)
                        u = store(u - alpha * q)
                        w = store(w - alpha * z)
                    out = (x, r, u, w, p, s, q, z, gamma, alpha, rr)
                    if needs_diff:
                        dx = alpha * alpha * psum(ldot(p, p))
                        if detect:
                            dx = jnp.where(bad, state[11], dx)
                        out = out + (dx,)
                    fire = None
                    if health is not None:
                        kk = k if k_offset is None else k + k_offset

                        def compute_gap():
                            return _health.relative_gap(b - spmv(x), r,
                                                                                pdot, bnrm2, sdt)

                        aud, fire = _health.audit_update(
                            aud, health, kk, compute_gap)
                        aud = _health.stall_update(aud, health,
                                                   rr < rr_prev)
                        if health.abft:
                            # checksum test of this iteration's n = A m
                            aud = _health.abft_update(
                                aud, health, kk, nvec, m, cvec,
                                pdot3_fused, sdt, nglobal)
                    if detect:
                        flag = bad
                        if health is not None:
                            flag = flag | _health.trip(aud, health)
                        out = out + (flag,)
                    if health is not None:
                        out = out + (aud,)
                    if trace:
                        audit_col = (_health.ring_gap(aud, fire, sdt)
                                     if health is not None else None)
                        out = out + (telemetry.ring_record(
                            buf, k, gamma, alpha, beta, denom,
                            audit=audit_col),)
                    if progress:
                        telemetry.heartbeat(k, gamma, progress,
                                            leader=leader,
                                            what="dist-cg")
                    return out

                if carry is not None:
                    init_state = (x0,) + tuple(carry)
                    rr0 = carry[9]
                else:
                    init_state = (x0, r, u0, w, zeros, zeros, zeros,
                                  zeros, inf, inf, rr0)
                init_state = init_state + ((inf,) if needs_diff else ())
                if detect:
                    init_state = init_state + (jnp.asarray(False),)
                if health is not None:
                    init_state = init_state + (_health.audit_init(sdt,
                                                                  health),)
                if trace:
                    init_state = init_state + (telemetry.ring_init(
                        trace, sdt, audit=health is not None),)
                bad_i = -1 - (1 if trace else 0) - (
                    1 if health is not None else 0)
                k, state, done = run_iter(
                    pbody, init_state, lambda s: s[10],
                    (lambda s: s[11]) if needs_diff else (lambda s: inf),
                    init_gamma=rr0,
                    bad_of=(lambda s: s[bad_i]) if detect else None)
                x, r_fin = state[0], state[1]
                dxsqr = state[11] if needs_diff else inf
                breakdown = state[bad_i] if detect else jnp.asarray(False)
                tbuf = state[-1] if trace else None
                aud_out = (state[-2] if trace else state[-1]) \
                    if health is not None else None
                rnrm2 = jnp.sqrt(pdot(r_fin, r_fin))
                # stale-test consistency: see jax_cg._cg_pipelined_program
                done = jnp.logical_or(done, rnrm2 <= res_tol)
            else:
                if carry is None:
                    w = spmv(r)
                zeros = jnp.zeros_like(b)

                def body(k, state):
                    if trace:
                        buf, state = state[-1], state[:-1]
                    if health is not None:
                        aud, state = state[-1], state[:-1]
                    x, r, w, p, t, z, gamma_prev, alpha_prev = state[:8]
                    # the pipelined variant's single fused allreduce:
                    # both scalars in one psum (cgcuda.c:1730-1737)
                    # single fused allreduce of both scalars
                    gamma, delta = pdot2_fused(r, r, w, r)
                    if fault is not None:
                        delta = fault.apply_dot(delta, k)
                    q = spmv(w, k)  # overlaps the psum under XLA's scheduler
                    # the SpMV input, before the update rebinds w (the
                    # ABFT check verifies q against THIS vector)
                    w_in = w
                    beta = gamma / gamma_prev
                    denom = delta - beta * (gamma / alpha_prev)
                    if detect:
                        # jax_cg._cg_pipelined_program's guard: the
                        # flag is NOT gamma_next-deferred here (the
                        # pipelined poison surfaces in the next
                        # iteration's (w, r) reduction instead)
                        bad, alpha = _breakdown_guard(gamma, denom)
                        if health is not None:
                            # sign anomaly (jax_cg rationale)
                            bad = bad | (gamma < 0)
                            alpha = jnp.where(bad, jnp.zeros_like(alpha),
                                              alpha)
                    else:
                        alpha = gamma / denom
                    z = store(q + beta * z)
                    t = store(w + beta * t)
                    p = store(r + beta * p)
                    if detect:
                        x = store(jnp.where(bad, x, x + alpha * p))
                        r = store(jnp.where(bad, r, r - alpha * t))
                        w = store(jnp.where(bad, w, w - alpha * z))
                    else:
                        x = store(x + alpha * p)
                        r = store(r - alpha * t)
                        w = store(w - alpha * z)
                    out = (x, r, w, p, t, z, gamma, alpha)
                    if needs_diff:
                        dx = alpha * alpha * psum(ldot(p, p))
                        if detect:
                            dx = jnp.where(bad, state[8], dx)
                        out = out + (dx,)
                    fire = None
                    if health is not None:
                        kk = k if k_offset is None else k + k_offset

                        def compute_gap():
                            return _health.relative_gap(b - spmv(x), r,
                                                                                pdot, bnrm2, sdt)

                        aud, fire = _health.audit_update(
                            aud, health, kk, compute_gap)
                        aud = _health.stall_update(aud, health,
                                                   gamma < gamma_prev)
                        if health.abft:
                            # checksum test of this iteration's q = A w
                            # (w_in: the pre-update input)
                            aud = _health.abft_update(
                                aud, health, kk, q, w_in, cvec,
                                pdot3_fused, sdt, nglobal)
                    if detect:
                        flag = bad
                        if health is not None:
                            flag = flag | _health.trip(aud, health)
                        out = out + (flag,)
                    if health is not None:
                        out = out + (aud,)
                    if trace:
                        # carried gamma (stale by one, like the
                        # convergence test); alpha denominator in the
                        # pAp slot (jax_cg._cg_pipelined_program)
                        audit_col = (_health.ring_gap(aud, fire, sdt)
                                     if health is not None else None)
                        out = out + (telemetry.ring_record(
                            buf, k, gamma, alpha, beta, denom,
                            audit=audit_col),)
                    if progress:
                        telemetry.heartbeat(k, gamma, progress,
                                            leader=leader, what="dist-cg")
                    return out

                # stale-gamma convergence test (see jax_cg): s[6] is the
                # psum'd ||r||^2 from before the update
                if carry is not None:
                    init_state = (x0,) + tuple(carry)
                    init_gamma = carry[5]
                else:
                    init_state = (x0, r, w, zeros, zeros, zeros, inf, inf)
                    init_gamma = gamma
                init_state = init_state + ((inf,) if needs_diff else ())
                if detect:
                    init_state = init_state + (jnp.asarray(False),)
                if health is not None:
                    init_state = init_state + (_health.audit_init(sdt,
                                                                  health),)
                if trace:
                    init_state = init_state + (telemetry.ring_init(
                        trace, sdt, audit=health is not None),)
                bad_i = -1 - (1 if trace else 0) - (
                    1 if health is not None else 0)
                k, state, done = run_iter(
                    body, init_state, lambda s: s[6],
                    (lambda s: s[8]) if needs_diff else (lambda s: inf),
                    init_gamma=init_gamma,
                    bad_of=(lambda s: s[bad_i]) if detect else None)
                x, r_fin = state[0], state[1]
                dxsqr = state[8] if needs_diff else inf
                breakdown = state[bad_i] if detect else jnp.asarray(False)
                tbuf = state[-1] if trace else None
                aud_out = (state[-2] if trace else state[-1]) \
                    if health is not None else None
                rnrm2 = jnp.sqrt(pdot(r_fin, r_fin))
                # stale-test consistency: see jax_cg._cg_pipelined_program
                done = jnp.logical_or(done, rnrm2 <= res_tol)

            # breakdown-at-the-floor consistency (jax_cg rationale): a
            # flagged exit whose residual already meets tolerance is
            # convergence, not breakdown
            breakdown = breakdown & ~done
            dxnrm2 = jnp.sqrt(dxsqr)
            out = (x[None], k, rnrm2, r0nrm2, bnrm2, x0nrm2, dxnrm2,
                   done, breakdown)
            out = out + ((tbuf,) if trace else ())
            # the audit vector rides after the ring so the existing
            # out[9] = ring fetch in solve() is untouched
            out = out + ((aud_out,) if health is not None else ())
            if state_io:
                # the final loop carry, strictly last (checkpoint.
                # carry_names order minus x, which rides the result):
                # vector leaves re-stack the parts axis, psum'd scalars
                # stay replicated
                core = state[1:1 + len(c_names)]
                out = out + tuple(v[None] if v.ndim else v
                                  for v in core)
            return out

        with_precond = precond_spec is not None
        if single_shard and not prob.halo.has_ghosts:
            # one shard, no halo: shard_body runs as a PLAIN jit program
            # (the stacked (1, ...) leading axes are stripped inside it
            # either way).  Skipping shard_map avoids its manual-
            # sharding boundary entirely, so XLA optimises the loop
            # exactly like the single-chip solver's.
            @functools.partial(jax.jit,
                               static_argnames=("unbounded", "needs_diff",
                                                "detect"))
            def program(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                        tols, maxits, unbounded, needs_diff,
                        detect=False, mstate=None, carry=None,
                        k_offset=None):
                return shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt,
                                  b, x0, tols, maxits, mstate=mstate,
                                  unbounded=unbounded,
                                  needs_diff=needs_diff, detect=detect,
                                  carry=carry, k_offset=k_offset)

            return program

        pspec = P(PARTS_AXIS)
        rspec = P()
        # pspec acts as a pytree prefix for the la/ga tuples (and the
        # mstate pytree when a preconditioner is armed: every state
        # leaf carries a leading parts axis, scalars tiled)
        in_specs = (pspec, pspec,                              # blocks
                    pspec, pspec, pspec, pspec, pspec,         # halo, counts
                    pspec, pspec,                              # b, x0
                    rspec, rspec)                              # tols, maxits
        if with_precond:
            in_specs = in_specs + (pspec,)                     # mstate
        # the telemetry ring (psum'd scalars) and the audit vector
        # (psum'd gap) are replicated
        out_specs = (pspec,) + (rspec,) * (
            8 + (1 if trace else 0)
            + (1 if self.health_spec is not None else 0))
        # the state_io carry: vector leaves shard like x, psum'd
        # scalars replicate (checkpoint.carry_names order)
        carry_specs = tuple(rspec if nm in SCALAR_LEAVES else pspec
                            for nm in c_names)
        if state_io:
            out_specs = out_specs + carry_specs

        @functools.partial(jax.jit,
                           static_argnames=("unbounded", "needs_diff",
                                            "detect"))
        def program(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                    tols, maxits, unbounded, needs_diff, detect=False,
                    mstate=None, carry=None, k_offset=None):
            extra = (mstate,) if with_precond else ()
            specs = in_specs
            if carry is not None:
                extra = extra + (tuple(carry),)
                specs = specs + (carry_specs,)
            if k_offset is not None:
                extra = extra + (k_offset,)
                specs = specs + (rspec,)

            def smb(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols,
                    maxits, *rest):
                i = 0
                ms = cr = ko = None
                if with_precond:
                    ms, i = rest[i], i + 1
                if carry is not None:
                    cr, i = rest[i], i + 1
                if k_offset is not None:
                    ko, i = rest[i], i + 1
                return shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt,
                                  b, x0, tols, maxits, mstate=ms,
                                  unbounded=unbounded,
                                  needs_diff=needs_diff, detect=detect,
                                  carry=cr, k_offset=ko)

            return _shard_map(
                smb,
                mesh=self.mesh, in_specs=specs, out_specs=out_specs,
            )(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols, maxits,
              *extra)

        return program

    def _compile_ca(self, fault=None):
        """Communication-avoiding recurrence programs: s-step CG (one
        Gram allreduce per s-iteration block) and deep-pipelined
        p(l)-CG (one fused 2l+2-scalar window allreduce per iteration),
        shard_map'd over the SAME halo'd SpMV / psum plumbing as the
        hand-built programs.  The recurrence math itself -- basis
        construction, coefficient updates, the stream-Cholesky window
        bookkeeping -- is the same code the single-device tier runs
        (recurrence.run_sstep_loop / run_pl_loop): a recurrence lands
        once in the builder and rides every tier."""
        from acg_tpu.recurrence import (TierOps, run_pl_loop,
                                        run_sstep_loop)
        prob = self.problem
        algo = self.algo
        axis = PARTS_AXIS
        comm = self.comm
        interpret = self._interpret
        trace = self.trace
        progress = self.progress
        health = self.health_spec
        dist_spmv = make_dist_spmv(prob, comm, interpret,
                                   kernels=self.kernels, fault=fault)
        single_shard = self.mesh.devices.size == 1

        def psum(v):
            return v if single_shard else lax.psum(v, axis)

        def shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                       tols, maxits, lam, unbounded=False):
            la, ga = (jax.tree.map(lambda a: a[0], t) for t in (la, ga))
            sidx, gsrc, gval, scnt, rcnt, b, x0 = (
                a[0] for a in (sidx, gsrc, gval, scnt, rcnt, b, x0))
            maxits = maxits.astype(jnp.int32)
            dtype = b.dtype
            sdt = acc_dtype(dtype)
            store = ((lambda v: v.astype(dtype)) if sdt != dtype
                     else (lambda v: v))
            res_atol, res_rtol = tols[0], tols[1]
            pidx = None
            if fault is not None:
                pidx = (jnp.int32(0) if single_shard
                        else lax.axis_index(axis))

            def spmv(x, k=None):
                return dist_spmv(x, la, ga, sidx, gsrc, gval, scnt,
                                 rcnt, k=k, pidx=pidx)

            def ldot(a, c):
                return jnp.dot(a, c, preferred_element_type=sdt)

            pdot = make_pdot(psum, ldot, sdt, False)
            ops = TierOps(spmv=spmv, dot=pdot, psum_stack=psum,
                          store=store, sdt=sdt)
            leader = None
            if progress and not single_shard:
                leader = lax.axis_index(axis) == jnp.int32(0)
            bnrm2 = jnp.sqrt(pdot(b, b))
            x0nrm2 = jnp.sqrt(pdot(x0, x0))
            r = b - spmv(x0)
            gamma = pdot(r, r)
            r0nrm2 = jnp.sqrt(gamma)
            res_tol = jnp.maximum(res_atol, res_rtol * r0nrm2)
            inf = jnp.asarray(jnp.inf, sdt)
            lam_t = (lam[0].astype(sdt), lam[1].astype(sdt))
            what = algo.solver_name("dist-cg")
            if algo.kind == "sstep":
                x, k, gamma_f, bad, done, extras = run_sstep_loop(
                    ops, algo.param, algo.basis, lam_t, b, x0, r,
                    gamma, res_tol, maxits, unbounded, fault=fault,
                    trace=trace, progress=progress, health=health,
                    what=what, leader=leader, bnrm2=bnrm2)
                rnrm2 = jnp.sqrt(jnp.maximum(gamma_f, 0.0))
            else:
                eta = r0nrm2
                z0 = store(r / jnp.where(eta == 0, 1.0, eta))
                x, k, q, conv, bad, extras = run_pl_loop(
                    ops, algo.param, lam_t, x0, z0, eta, gamma,
                    res_tol, maxits, unbounded, fault=fault,
                    trace=trace, progress=progress, what=what,
                    leader=leader)
                x = store(x)
                rnrm2 = jnp.abs(q)
                done = (~bad) if unbounded else conv
            breakdown = bad & ~done
            out = (x[None], k, rnrm2, r0nrm2, bnrm2, x0nrm2, inf,
                   done, breakdown)
            return out + extras

        pspec = P(PARTS_AXIS)
        rspec = P()
        in_specs = (pspec, pspec, pspec, pspec, pspec, pspec, pspec,
                    pspec, pspec, rspec, rspec, rspec)
        out_specs = (pspec,) + (rspec,) * (
            8 + (1 if trace else 0)
            + (1 if health is not None else 0))

        @functools.partial(jax.jit,
                           static_argnames=("unbounded", "needs_diff",
                                            "detect"))
        def program(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols,
                    maxits, lam, unbounded, needs_diff, detect=False):
            # needs_diff / detect ride the signature for dispatch
            # compatibility: diff criteria are refused at solve time,
            # and the CA programs always carry their breakdown flag
            if single_shard and not prob.halo.has_ghosts:
                return shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt,
                                  b, x0, tols, maxits, lam,
                                  unbounded=unbounded)

            def smb(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols,
                    maxits, lam):
                return shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt,
                                  b, x0, tols, maxits, lam,
                                  unbounded=unbounded)

            return _shard_map(
                smb, mesh=self.mesh, in_specs=in_specs,
                out_specs=out_specs,
            )(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols,
              maxits, lam)

        return program

    def _ensure_lam(self, dev_args):
        """Cached (lmin, lmax) interval for the CA recurrences: the
        mesh power iteration (_power_lmax) through this tier's own
        halo'd SpMV, with the recurrence module's spectral headroom."""
        if self._lam is None:
            from acg_tpu.recurrence import LAM_SAFETY
            if self.algo is not None and self.algo.needs_lam:
                self._lam = (0.0,
                             self._power_lmax(dev_args) * LAM_SAFETY)
            else:
                self._lam = (0.0, 0.0)
        return self._lam

    def _solver_name(self) -> str:
        if self.algo is not None:
            return self.algo.solver_name("dist-cg")
        return "dist-cg-pipelined" if self.pipelined else "dist-cg"

    def _interior_rows(self) -> np.ndarray:
        """Cached stacked interior row lists (the fused tier's split;
        host numpy, placed by device_args like the halo plan)."""
        if getattr(self, "_irows", None) is None:
            self._irows = interior_border_split(self.problem)
        return self._irows

    # -- preconditioner state ---------------------------------------------

    def _power_lmax(self, dev_args, iters=None) -> float:
        """Power-iteration lambda_max over the SAME halo'd distributed
        SpMV the solve programs run, compiled once at setup (the
        Chebyshev tier's spectral estimate).  Norms psum across the
        mesh, so every shard (and controller) derives the identical
        scalar."""
        from acg_tpu.precond import POWER_ITERS
        iters = POWER_ITERS if iters is None else int(iters)
        b, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = dev_args
        prob = self.problem
        axis = PARTS_AXIS
        dist_spmv = make_dist_spmv(prob, self.comm, self._interpret,
                                   kernels=self.kernels)
        single_shard = self.mesh.devices.size == 1
        sdt = acc_dtype(np.dtype(prob.vdtype))

        def shard(la, ga, sidx, gsrc, gval, scnt, rcnt, v):
            la, ga = (jax.tree.map(lambda a: a[0], t) for t in (la, ga))
            sidx, gsrc, gval, scnt, rcnt, v = (
                a[0] for a in (sidx, gsrc, gval, scnt, rcnt, v))

            def psum(s):
                return s if single_shard else lax.psum(s, axis)

            def spmv(x):
                return dist_spmv(x, la, ga, sidx, gsrc, gval, scnt, rcnt)

            def ldot(a, c):
                return jnp.dot(a, c, preferred_element_type=sdt)

            def it(_, v):
                w = spmv(v)
                return (w.astype(sdt)
                        / jnp.sqrt(psum(ldot(w, w)))).astype(v.dtype)

            v = jax.lax.fori_loop(0, iters, it, v)
            w = spmv(v)
            return psum(ldot(v, w)) / psum(ldot(v, v))

        rng = np.random.default_rng(0)
        v0 = put_global(prob.scatter(rng.standard_normal(prob.n)),
                        sharding=self._sharding)
        if single_shard and not prob.halo.has_ghosts:
            out = jax.jit(shard)(la, ga, sidx, gsrc, gval, scnt, rcnt, v0)
        else:
            pspec = P(PARTS_AXIS)
            out = jax.jit(_shard_map(
                shard, mesh=self.mesh,
                in_specs=(pspec,) * 8, out_specs=P(),
            ))(la, ga, sidx, gsrc, gval, scnt, rcnt, v0)
        return float(out)

    def _ensure_precond_state(self, dev_args=None):
        """Build (once) the stacked preconditioner state and place it on
        the mesh: jacobi/bjacobi from each part's LOCAL host blocks (no
        communication -- diagonal entries are owned x owned by
        construction), cheby from the power iteration above.  Every
        leaf carries a leading parts axis (scalars tiled), so ONE
        pytree-prefix spec shards the whole state."""
        if self.precond_spec is None or self._mstate is not None:
            return self._mstate
        from acg_tpu import precond as precond_mod
        prob = self.problem
        sdt = np.dtype(acc_dtype(np.dtype(prob.vdtype)))
        spec = self.precond_spec
        if spec.kind == "jacobi":
            host = precond_mod.stacked_jacobi_state(prob, sdt)
        elif spec.kind == "bjacobi":
            host = precond_mod.stacked_bjacobi_state(prob, spec.block, sdt)
        else:
            if dev_args is None:
                dev_args = getattr(self, "_last_dev_args", None)
            if dev_args is None:
                raise RuntimeError("cheby state needs the placed device "
                                   "arguments (solve/lower build them)")
            lmax = self._power_lmax(dev_args) * precond_mod.CHEBY_SAFETY
            lmin = lmax / precond_mod.CHEBY_RATIO
            self._precond_lams = (lmin, lmax)
            host = (np.full((prob.nparts,), lmin, sdt),
                    np.full((prob.nparts,), lmax, sdt))
        put = functools.partial(put_global, sharding=self._sharding)
        self._mstate = jax.tree.map(put, host)
        return self._mstate

    # -- public solve ------------------------------------------------------

    def _solve_dtype(self):
        """The dtype solve inputs scatter to (the JaxCGSolver hook's
        twin, shared with the perfmodel tier): the problem's vector
        dtype, except the replacement tier's outer iteration owns b/x0
        in f32."""
        return np.dtype(np.float32 if self.replace_every
                        else self.problem.vdtype)

    def device_args(self, b_global: np.ndarray,
                    x0: np.ndarray | None = None):
        """Scatter + place every solve input on the mesh (the upload
        stage of ``acgsolvercuda_init``, ``cgcuda.c:143-332``); shared
        by :meth:`solve` and the per-op profiler.

        Under ``replace_every`` the outer iteration owns b/x0 in f32
        (scattering them to bf16 would bake a u_bf16 backward error
        into every replaced residual)."""
        prob = self.problem
        dtype = self._solve_dtype()
        put = functools.partial(put_global, sharding=self._sharding)
        b = put(prob.scatter(np.asarray(b_global), dtype=dtype))
        x0 = put(prob.scatter(np.asarray(x0), dtype=dtype)
                 if x0 is not None
                 else np.zeros((prob.nparts, prob.nmax_owned), dtype=dtype))
        la = jax.tree.map(put, prob.local.arrays)
        ga = jax.tree.map(put, (prob.ghost.rows, prob.ghost.data,
                                prob.ghost.cols))
        if self.kernels == "fused":
            # the interior row lists ride the ghost-block tuple (the
            # split SpMV consumes both row sets together); the pytree-
            # prefix shard specs cover the longer tuple unchanged
            ga = ga + (put(self._interior_rows()),)
        sidx = put(prob.halo.send_idx)
        gsrc = put(prob.halo.ghost_src)
        gval = put(prob.halo.ghost_valid)
        scnt_np, rcnt_np = prob.neighbor_counts()
        return (b, x0, la, ga, sidx, gsrc, gval,
                put(scnt_np), put(rcnt_np))

    def lower_solve(self, b_global, x0=None, criteria=None):
        """Lower (but do not run) the EXACT whole-solve SPMD program this
        configuration dispatches for ``(b, x0, criteria)`` and return
        the ``jax.stages.Lowered`` handle -- the observability hook the
        perfmodel tier (:mod:`acg_tpu.perfmodel`) compiles to extract
        the compiler's cost/memory analysis.  Same program object, same
        static arguments and same input avals as :meth:`solve`, so the
        lowered text is byte-identical to a clean solve's (asserted in
        tests/test_hlo_structure.py); detection mirrors a clean solve
        (armed iff a recovery policy is set -- never the fault
        injector)."""
        crit = criteria or StoppingCriteria()
        if self.replace_every and crit.needs_diff:
            raise ValueError("replace_every supports residual criteria "
                             "only")
        if self.algo is not None and crit.needs_diff:
            raise ValueError(f"{self.algo} supports residual criteria "
                             f"only")
        if self.kernels == "fused" and crit.needs_diff:
            raise ValueError("kernels='fused' supports residual "
                             "criteria only")
        sdt = acc_dtype(np.dtype(self.problem.vdtype))
        dev = self.device_args(np.asarray(b_global), x0)
        b, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = dev
        tols = jnp.asarray([crit.residual_atol, crit.residual_rtol,
                            crit.diff_atol, crit.diff_rtol], dtype=sdt)
        program = self._program_for(None)
        kwargs = dict(unbounded=crit.unbounded,
                      needs_diff=crit.needs_diff,
                      detect=self._detect(None))
        if self.precond_spec is not None:
            self._last_dev_args = dev
            kwargs["mstate"] = self._ensure_precond_state(dev)
        args = (la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                tols, jnp.int32(crit.maxits))
        if self.algo is not None:
            lam = self._ensure_lam(dev)
            args = args + ((jnp.asarray(lam[0], sdt),
                            jnp.asarray(lam[1], sdt)),)
        return program.lower(*args, **kwargs)

    def _detect(self, fault) -> bool:
        """Breakdown-flag arming shared by solve() and lower_solve (the
        jax_cg._detect twin): recovery, an active injector, or a health
        spec whose detectors trip the breakdown path."""
        return (self.recovery is not None or fault is not None
                or (self.health_spec is not None
                    and self.health_spec.arms_detect))

    def comm_profile(self) -> dict:
        """Static per-iteration communication ledger (the perfmodel
        tier): per-neighbour halo payload bytes from the halo plans,
        psum/allreduce scalar counts and bytes, and ring-hop estimates
        from the 1-D mesh shape.  Pure host arithmetic -- nothing here
        touches the device or the compiled programs.

        Counts describe the direct classic/pipelined loop: one halo'd
        SpMV per iteration, classic = 2 psums of 1 scalar each,
        pipelined = 1 FUSED psum of 2 scalars (the communication-
        avoiding property tests/test_hlo_structure.py pins in the HLO);
        compensated dots double each payload (hi+lo pairs).  The
        replacement tier runs the same pattern per inner iteration plus
        one f32 exchange per segment."""
        prob = self.problem
        P = int(prob.nparts)
        dbl = int(np.dtype(prob.vdtype).itemsize)
        sdl = int(np.dtype(acc_dtype(np.dtype(prob.vdtype))).itemsize)
        scnt, _rcnt = prob.neighbor_counts()
        neighbors = []
        total = 0
        max_hops = 0
        for p in range(P):
            for q in range(P):
                c = int(scnt[p, q])
                if c == 0 or p == q:
                    continue
                # ring distance over the 1-D parts axis: the ICI-hop
                # estimate for a torus-linked pod slice
                hops = min(abs(p - q), P - abs(p - q))
                max_hops = max(max_hops, hops)
                total += c * dbl
                neighbors.append({"src": p, "dst": q, "bytes": c * dbl,
                                  "hops": hops})
        nred = 1 if self.pipelined else 2
        scal = ((2 if self.pipelined else 1)
                * (2 if self.precise_dots else 1))
        led = {
            "transport": self.comm,
            "nparts": P,
            "mesh_shape": {str(k): int(v)
                           for k, v in dict(self.mesh.shape).items()},
            "halo_exchanges_per_iteration": 1,
            # local-read multi-controller builds hold plans only for
            # this controller's parts (neighbor_counts leaves the rest
            # zero): the halo totals then cover the OWNED rows only --
            # marked so a consumer never mistakes a per-controller
            # partial for the pod-global volume
            **({"owned_parts_only": True,
                "owned_parts": [int(p) for p in prob.owned_parts]}
               if prob.owned_parts is not None else {}),
            "halo_bytes_per_iteration": int(total),
            "allreduce_per_iteration": int(nred),
            "allreduce_scalars": int(scal),
            "allreduce_bytes_per_iteration": int(nred * scal * sdl),
            "max_hops": int(max_hops),
            # what the transport ACTUALLY moves per exchange and shard:
            # windows are padded to the mesh-wide maximum count (the
            # NVSHMEM symmetric-buffer trick), so the wire sees the
            # padded plane -- (P-1) windows for the dma rotation
            # schedule, P for the all_to_all plane.  The commbench
            # calibration prices halo time over these bytes (its
            # sweeps use the same convention); the unpadded neighbour
            # totals above stay the VOLUME accounting
            "halo_plane_bytes_per_exchange": int(
                ((P - 1) if self.comm == "dma" else P)
                * int(getattr(prob.halo, "maxcnt", 0)) * dbl),
            # the ring distances this partition's edges span -- the key
            # that matches a commbench per-edge put/wait row to an
            # actual edge of this halo plan
            "ring_distances": sorted({n["hops"] for n in neighbors}),
        }
        if prob.operator is not None:
            # the matrix-free stencil ledger: who the operator is, and
            # what the "matrix read" actually costs per apply -- the
            # O(grid-side) coefficient tables (0 for constant
            # stencils), NOT nnz * itemsize.  --explain prices the
            # roofline's matrix-bytes term from this
            led["operator"] = prob.operator.identity()
            led["matrix_free"] = True
            led["matrix_bytes_per_spmv"] = int(
                prob.operator.table_bytes())
        if self.kernels == "fused":
            # the overlap declaration of the fused tier: how much
            # interior-SpMV work is available to hide the halo latency
            # behind.  perfmodel's --explain verdict prices it as
            # predicted exposed halo seconds = max(0, t_halo -
            # t_interior_spmv), confronted with the measured
            # solve-windowed overlap score when a --trace capture
            # exists
            irows = self._interior_rows()
            nint = int((irows < prob.nmax_owned).sum())
            nbor = int((np.asarray(prob.ghost.rows)
                        < prob.nmax_owned).sum())
            mat_b = int(np.dtype(prob.dtype).itemsize)
            matfree = prob.local.format == "matfree"
            idx_b = 0 if prob.local.format in ("dia", "matfree") else 4
            nnz_int = 0
            for p, s in enumerate(prob.subs):
                if s.A_local is None:
                    continue
                rnnz = np.diff(s.A_local.indptr)
                ir = irows[p]
                ir = ir[ir < s.nowned]
                nnz_int += int(rnnz[ir].sum())
            led["overlap"] = {
                "split": "interior|border",
                "interior_rows": nint,
                "border_rows": nbor,
                "interior_nnz": nnz_int,
                # HBM traffic of the interior SpMV phase: matrix reads
                # plus the x gather + y write over the interior rows
                # (matrix-free: the planes are generated, not read --
                # only the vector traffic remains)
                "interior_matrix_bytes": (
                    (0 if matfree else nnz_int * (mat_b + idx_b))
                    + 2 * nint * dbl),
            }
        if self.algo is not None:
            # communication-avoiding recurrences: the reduction
            # schedule is the recurrence's own declaration
            # (recurrence.reduction_schedule) -- fractional values are
            # exact per-iteration averages of per-block events (the
            # whole point: s-step's 1/s allreduce per iteration vs
            # classic's 2)
            from acg_tpu.recurrence import reduction_schedule
            sched = reduction_schedule(self.algo, False)
            led["algorithm"] = str(self.algo)
            led["allreduce_per_iteration"] = \
                sched["allreduce_per_iteration"]
            led["allreduce_scalars"] = sched["allreduce_scalars"]
            led["allreduce_bytes_per_iteration"] = (
                sched["allreduce_per_iteration"]
                * sched["allreduce_scalars"] * sdl)
            led["halo_exchanges_per_iteration"] = \
                sched["spmv_per_iteration"]
            led["halo_bytes_per_iteration"] = (
                total * sched["spmv_per_iteration"])
            for extra_key in ("iterations_per_reduction",
                              "reduction_latency_hidden"):
                if extra_key in sched:
                    led[extra_key] = sched[extra_key]
        if self.precond_spec is not None:
            # reclassify for PCG: cheby multiplies the halo pattern by
            # its degree (K extra SpMV-shaped exchanges per iteration);
            # jacobi/bjacobi move nothing.  The scalar fused into the
            # existing reductions ((r,z) / the 3-scalar pipelined psum)
            # widens payloads without adding collectives
            from acg_tpu.precond import comm_contribution
            pc = comm_contribution(self.precond_spec)
            extra = int(pc.get("halo_spmv_equivalents_per_apply", 0))
            led["halo_exchanges_per_iteration"] = 1 + extra
            led["halo_bytes_per_iteration"] = int(total) * (1 + extra)
            # widest reduction payload: pipelined PCG fuses 3 scalars,
            # classic PCG's second psum fuses 2 (doubled compensated)
            led["allreduce_scalars"] = ((3 if self.pipelined else 2)
                                        * (2 if self.precise_dots else 1))
            # TOTAL scalars per iteration, not nred x widest: both PCG
            # loops move 3 (classic: 1 + the 2-scalar fusion)
            led["allreduce_bytes_per_iteration"] = (
                3 * (2 if self.precise_dots else 1) * sdl)
            led["precond"] = pc
        if len(neighbors) > 64:
            led["neighbors_truncated"] = len(neighbors) - 64
            neighbors = neighbors[:64]
        led["neighbors"] = neighbors
        return led

    def solve(self, b_global: np.ndarray, x0: np.ndarray | None = None,
              criteria: StoppingCriteria | None = None,
              raise_on_divergence: bool = True, warmup: int = 0,
              host_result: bool = True) -> np.ndarray:
        """``host_result=False`` skips the global gather and returns the
        STACKED device array ((nparts, nmax_owned), sharded over the
        mesh) -- callers that stream per-part windows to disk
        (``--output`` distributed write) or feed another device
        computation never materialise the full vector anywhere, the
        point of the reference's rank-ordered distributed output
        (``mtxfile_fwrite_mpi_double``)."""
        if self.ckpt is not None:
            return self._solve_ckpt(b_global, x0=x0, criteria=criteria,
                                    raise_on_divergence=raise_on_divergence,
                                    warmup=warmup,
                                    host_result=host_result)
        crit = criteria or StoppingCriteria()
        st = self.stats
        st.criteria = crit
        prob = self.problem
        dtype = np.dtype(prob.vdtype)
        if self.replace_every and crit.needs_diff:
            raise ValueError("replace_every supports residual criteria "
                             "only")
        if self.kernels == "fused" and crit.needs_diff:
            raise ValueError("kernels='fused' supports residual "
                             "criteria only (the builder base program "
                             "carries no dx scalar)")

        from acg_tpu import faults
        self._crash_refusal()
        fault = faults.device_fault()
        if fault is not None and self.kernels == "fused":
            # the fused base program carries no breakdown flag: an
            # armed injector would poison the solve with nothing
            # downstream ever noticing (the replace_every rationale)
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "fault injection does not reach the fused "
                "interior/border program (kernels='fused'); inject "
                "into the classic/pipelined programs instead")
        if (fault is not None and fault.site == "halo"
                and not prob.halo.has_ghosts):
            # this topology performs no halo exchange: the armed
            # injector could never fire (the replace_every rationale)
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "halo fault injection needs a topology with ghost "
                "exchange; this problem has no halo (single part or "
                "fully decoupled partition)")
        if fault is not None and fault.part >= prob.nparts:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"fault spec targets part {fault.part}, but this mesh "
                f"has {prob.nparts} parts -- the fault could never "
                f"fire")
        if fault is not None and self.replace_every:
            # the replacement segments call the dist SpMV without the
            # global iteration index: an armed injector would silently
            # never fire (jax_cg rationale) -- refuse instead
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "fault injection does not reach the replacement-segment "
                "program (replace_every); inject into the direct "
                "classic/pipelined programs instead")
        if (self.algo is not None and fault is not None
                and self.algo.kind == "sstep"
                and fault.site in ("spmv", "sdc", "halo")
                and fault.iteration % self.algo.param != 0):
            # the s-step basis products carry the BLOCK-START iteration
            # index (jax_cg rationale): mid-block arming never fires
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"sstep:{self.algo.param} applies SpMV/halo faults at "
                f"block boundaries; arm an iteration that is a "
                f"multiple of {self.algo.param} (got "
                f"{fault.iteration})")
        if (self.algo is not None and fault is not None
                and self.algo.kind == "pl" and fault.site == "dot"):
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "dot fault injection has no site in the p(l) "
                "recurrence (its reductions are fused window matvecs); "
                "use spmv:, or the classic/pipelined/sstep programs")
        if (fault is not None and fault.site == "precond"
                and self.precond_spec is None):
            # no preconditioner armed: the apply the fault poisons
            # never runs (the replace_every rationale)
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "precond fault injection needs an armed preconditioner "
                "(--precond jacobi|bjacobi|cheby:K); this solve runs "
                "unpreconditioned CG")
        detect = self._detect(fault)
        from acg_tpu import telemetry
        if self._comm_downgrade is not None:
            # the capability-probe downgrade, recorded once as a
            # structured event so stats/metrics consumers see WHY this
            # solve ran the xla transport
            telemetry.record_event(st, "transport-downgrade",
                                   f"dma -> xla: {self._comm_downgrade}")
            self._comm_downgrade = None
        if fault is not None:
            telemetry.record_event(st, "fault-armed",
                                   f"{fault.site}:{fault.mode}"
                                   f"@{fault.iteration}")
        # an armed injector bakes into a solve-local program; the cached
        # pristine program serves every clean solve
        program = self._program_for(fault)

        t_xfer = time.perf_counter()
        with telemetry.annotate("transfer"):
            b, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = \
                self.device_args(b_global, x0)
        telemetry.add_timing(st, "transfer", time.perf_counter() - t_xfer)
        # tolerances in the scalar dtype (f32 for bf16 storage) so a 1e-9
        # rtol is not pre-rounded to 8 mantissa bits
        sdt = acc_dtype(dtype)
        tols = jnp.asarray([crit.residual_atol, crit.residual_rtol,
                            crit.diff_atol, crit.diff_rtol], dtype=sdt)
        kwargs = dict(unbounded=crit.unbounded, needs_diff=crit.needs_diff,
                      detect=detect)
        if self.precond_spec is not None:
            self._last_dev_args = (b, x0, la, ga, sidx, gsrc, gval,
                                   scnt, rcnt)
            kwargs["mstate"] = self._ensure_precond_state(
                self._last_dev_args)
        args = (la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols,
                jnp.int32(crit.maxits))
        if self.algo is not None:
            if crit.needs_diff:
                raise ValueError(f"{self.algo} supports residual "
                                 f"criteria only")
            lam = self._ensure_lam((b, x0, la, ga, sidx, gsrc, gval,
                                    scnt, rcnt))
            args = args + ((jnp.asarray(lam[0], sdt),
                            jnp.asarray(lam[1], sdt)),)
        # device_sync, not bare block_until_ready: see _platform (the
        # tunneled backend's block has been observed not to wait)
        from acg_tpu._platform import block_until_ready_works, device_sync
        block_until_ready_works()  # resolve the cached probe OUTSIDE timing
        t_warm = time.perf_counter()
        with telemetry.annotate("compile"):
            for _ in range(max(warmup, 0)):
                device_sync(program(*args, **kwargs)[0])
        if warmup > 0:
            telemetry.add_timing(st, "compile",
                                 time.perf_counter() - t_warm)

        def attempt_trace(out):
            """The ONE extra host fetch of a traced solve: the ring is
            replicated (psum'd scalars), so any controller's copy is
            the mesh's."""
            if not self.trace:
                return None
            # rspec output -> fully replicated: every process holds a
            # complete copy, np.asarray reads the local one
            return telemetry.ConvergenceTrace.from_ring(
                np.asarray(out[9]), int(out[1]),
                solver=self._solver_name())

        hl = self.health_spec is not None

        def attempt_aud(out):
            """The replicated audit vector (rides LAST, after the
            ring); one tiny rank-independent fetch per attempt."""
            return np.asarray(out[-1]) if hl else None

        t0 = time.perf_counter()
        with telemetry.annotate("solve"):
            out = program(*args, **kwargs)
            device_sync(out[0])
        niter = int(out[1])
        first_norms = None
        # first note_audit resets the summary, later attempts merge
        # (the jax_cg rationale: a recovered solve must still show the
        # worst gap of the whole solve); gap_tripped marks the latest
        # attempt's exit as an accuracy gate for the raise below
        aud_fresh = True
        gap_tripped = False
        if detect and bool(out[8]):
            # the recovery ladder (solvers.resilience): bounded restarts
            # from the recomputed true residual; a recurring breakdown
            # under the dma transport retires it for the xla
            # collectives; the final rung re-solves on the distributed
            # host oracle.  Multi-controller, every restart/abort
            # decision is error-agreed (erragree.agree_status inside the
            # driver), so the pod acts in unison.
            from acg_tpu.solvers.resilience import RecoveryDriver
            driver = RecoveryDriver(self.recovery, st, "dist-cg")
            pol = self.recovery
            x0_dev = args[8]
            # stats report the ORIGINAL solve's norms (jax_cg rationale)
            first_norms = (float(out[4]), float(out[5]), float(out[3]))
            abs_tol = max(crit.residual_atol,
                          crit.residual_rtol * float(out[3]))
            rtols = jnp.asarray([abs_tol, 0.0, crit.diff_atol,
                                 crit.diff_rtol], dtype=sdt)
            def restart_args(x_next):
                if not bool(jnp.isfinite(x_next).all()):
                    driver.record("iterate non-finite; restarting "
                                  "from the initial guess")
                    x_next = x0_dev
                remaining = max(crit.maxits - niter, 1)
                return (args[:8] + (x_next, rtols)
                        + (jnp.int32(remaining),) + args[11:])

            while bool(out[8]):
                k_done = int(out[1])
                if hl:
                    # audit evidence before the restart decision: the
                    # accuracy_degraded event marks a gap trip apart
                    # from an arithmetic breakdown (jax_cg rationale)
                    from acg_tpu import health as health_mod
                    gap_tripped = health_mod.note_audit(
                        st, attempt_aud(out), self.health_spec,
                        "dist-cg", fresh=aud_fresh)
                    aud_fresh = False
                if self.trace:
                    # the trajectory that led INTO the breakdown
                    st.trace = self.last_trace = attempt_trace(out)
                    driver.log_trace_window(st.trace)
                if gap_tripped and self.health_spec.action == "abort":
                    # host-tier parity (the jax_cg rationale): abort is
                    # a hard stop, the restart budget and the transport
                    # fallback belong to replace.  The predicate comes
                    # from the psum'd (replicated) audit vector, so
                    # every controller raises in unison
                    st.tsolve += time.perf_counter() - t0
                    st.converged = False
                    raise BreakdownError(
                        f"dist-cg: true-residual gap "
                        f"{st.health.get('gap_max', 0.0):.3e} exceeds "
                        f"threshold {self.health_spec.threshold:g} at "
                        f"iteration {niter} (--on-gap abort)")
                if (self.comm == "dma" and driver.restarts >= 1
                        and pol is not None and pol.fallback_comm):
                    # a restart did not cure it: suspect the one-sided
                    # transport and retire it for this solver.  The
                    # fallback is its OWN rung -- it gets an attempt on
                    # the new transport without consuming the restart
                    # budget (otherwise max_restarts=1 would retire the
                    # transport and give up before ever trying it).
                    # The pristine program is invalidated and rebuilt
                    # LAZILY -- eagerly compiling it here alongside the
                    # fault-armed one would waste a whole multi-second
                    # XLA compile inside the recovery path
                    st.nbreakdowns += 1
                    driver.on_fallback("fallback: halo transport "
                                       "dma -> xla")
                    self.comm = "xla"
                    self._program = None
                    self._ckpt_program = None
                    if fault is not None:
                        fault = fault.shift(k_done)
                    program = self._program_for(fault)
                    args = restart_args(out[0])
                    out = program(*args, **kwargs)
                    device_sync(out[0])
                    niter += int(out[1])
                    continue
                if driver.on_breakdown(k_done):
                    x_next = out[0]
                    if fault is not None:
                        if (self.algo is not None
                                and self.algo.kind == "sstep"
                                and fault.device_site
                                and fault.iteration <= k_done):
                            # fired inside a frozen basis block: vanish,
                            # never rebase (jax_cg rationale)
                            fault = None
                        elif (self.algo is not None
                              and self.algo.kind == "pl"
                              and fault.device_site):
                            # shift in the z-counter frame (j = adv + l
                            # at breakdown -- jax_cg rationale): a
                            # fired fault vanishes instead of
                            # re-triggering forever
                            fault = fault.shift(
                                k_done + self.algo.param + 1)
                        else:
                            fault = fault.shift(k_done)
                        program = self._program_for(fault)
                    if self.precond_spec is not None:
                        # preserve finite preconditioner state across
                        # the restart, rebuild it when poisoned
                        from acg_tpu.precond import refresh_state
                        if refresh_state(self, driver):
                            kwargs["mstate"] = self._mstate
                    args = restart_args(x_next)
                    out = program(*args, **kwargs)
                    device_sync(out[0])
                    niter += int(out[1])
                    continue
                can_host = (pol is not None and pol.fallback_host
                            and prob.owned_parts is None
                            and all(s.A_local is not None
                                    for s in prob.subs))
                if can_host:
                    driver.on_fallback("fallback: distributed host "
                                       "reference solver")
                    st.tsolve += time.perf_counter() - t0
                    return self._host_fallback(b_global, crit,
                                               raise_on_divergence,
                                               host_result)
                st.tsolve += time.perf_counter() - t0
                st.converged = False
                if gap_tripped:
                    # the jax_cg parity: a gap-gated exit names the
                    # accuracy gate, not the arithmetic diagnosis
                    raise BreakdownError(
                        f"dist-cg: true-residual gap "
                        f"{st.health.get('gap_max', 0.0):.3e} exceeds "
                        f"threshold {self.health_spec.threshold:g} at "
                        f"iteration {niter} (--on-gap "
                        f"{self.health_spec.action}); "
                        f"{st.nrestarts} restart(s) exhausted and no "
                        f"fallback available")
                raise driver.give_up(niter, float(out[2]))
        t_solve = time.perf_counter() - t0
        st.tsolve += t_solve
        telemetry.add_timing(st, "solve", t_solve)
        if self.trace:
            st.trace = self.last_trace = attempt_trace(out)

        x_st, k, rnrm2, r0nrm2, bnrm2, x0nrm2, dxnrm2, done = out[:8]
        st.nsolves += 1
        st.niterations = niter
        st.ntotaliterations += niter
        st.bnrm2, st.x0nrm2, st.r0nrm2 = (
            first_norms if first_norms is not None
            else (float(bnrm2), float(x0nrm2), float(r0nrm2)))
        st.rnrm2 = float(rnrm2)
        st.dxnrm2 = float(dxnrm2)
        st.converged = bool(done) or crit.unbounded
        if hl:
            from acg_tpu import health as health_mod
            health_mod.note_audit(st, attempt_aud(out),
                                  self.health_spec, "dist-cg",
                                  fresh=aud_fresh)
        # service-metrics tier (no-op disarmed): one completed solve,
        # plus this solve's halo/psum traffic folded out of the static
        # comm ledger (comm_profile, the perfmodel tier's hook)
        from acg_tpu import metrics
        metrics.record_solve(t_solve, niter, st.converged,
                             solver=self._solver_name())
        metrics.observe_solver_comm(self, niter)
        self._account_ops(st, niter)

        if host_result:
            x = prob.gather(get_global(x_st))
            st.fexcept_arrays = [x]
        else:
            x = x_st
            # device-side scans; only two bools cross the wire (the
            # JaxCGSolver host_result=False convention)
            has_nan = bool(jnp.isnan(x_st).any())
            has_inf = bool(jnp.isinf(x_st).any())
            st.fexcept_arrays = [np.asarray([np.nan if has_nan else 0.0,
                                             np.inf if has_inf else 0.0])]
        if not st.converged and raise_on_divergence:
            raise NotConvergedError(
                f"{niter} iterations, residual {st.rnrm2:.3e}")
        return x

    def _crash_refusal(self) -> None:
        """``crash:exit`` fires from the checkpoint chunk driver between
        snapshots: armed without --ckpt it could never fire -- refuse
        instead of reporting a clean 'fault-tested' solve (the
        fault-injector discipline)."""
        from acg_tpu import faults
        spec = faults.active_fault()
        if (spec is not None and spec.site == "crash"
                and (self.ckpt is None or self.ckpt.path is None)):
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "crash:exit fires from the checkpoint chunk driver "
                "between snapshots; arm --ckpt FILE --ckpt-every K "
                "(a crash with no snapshot to resume from proves "
                "nothing)")

    def _account_ops(self, st, niter: int) -> None:
        """Analytic flop/byte census of ``niter`` iterations on this
        configuration -- shared by the plain and checkpoint-chunked
        solve paths so their stats blocks cannot drift apart."""
        prob = self.problem
        dtype = np.dtype(prob.vdtype)
        n = prob.n
        # CA recurrences run spmv_per_iteration SpMV-equivalents (the
        # s-step matrix-powers basis: (2s-1)/s), declared once by
        # recurrence.reduction_schedule -- the same number the jax_cg
        # tier's census and the comm ledger report, so the two tiers'
        # stats for the identical algorithm cannot drift apart
        spmv_eq = 1.0
        if self.algo is not None:
            from acg_tpu.recurrence import reduction_schedule
            spmv_eq = reduction_schedule(
                self.algo, False)["spmv_per_iteration"]
        st.nflops += (cg_flops_per_iteration(prob.nnz_total, n, self.pipelined)
                      * niter + 3.0 * prob.nnz_total + 2.0 * n
                      + 3.0 * prob.nnz_total * (spmv_eq - 1.0) * niter)
        dbl = dtype.itemsize
        # matrix bytes in the matrix dtype (differs from vectors under
        # mixed); DIA local blocks read no index arrays, ELL reads 4 B
        mat_dbl = np.dtype(prob.dtype).itemsize
        idx_b = 0 if prob.local.format in ("dia", "matfree") else 4
        # matrix-free local blocks read no planes at all -- the gemv
        # row's bytes are the generated-operand vector traffic plus
        # the O(grid-side) coefficient tables
        mat_read = (prob.operator.table_bytes()
                    if prob.operator is not None
                    else prob.nnz_total * (mat_dbl + idx_b))
        ngemv = int(niter * spmv_eq) + 1
        st.ops["gemv"].add(ngemv, 0.0,
                           (mat_read + 2 * n * dbl) * ngemv)
        # op census matching the single-device/eager accounting
        # (jax_cg.solve / host_cg.solve): the convergence test's (r, r)
        # is the nrm2 class, classic CG's p = r setup the one copy --
        # these were the permanently-zero stats rows (the reference
        # fills both, cgcuda.c:1942-1957)
        st.ops["dot"].add(niter, 0.0, 2 * n * dbl * niter)
        st.ops["nrm2"].add(niter + 1, 0.0, n * dbl * (niter + 1))
        st.ops["axpy"].add(3 * niter, 0.0, 3 * n * dbl * 3 * niter)
        if not self.pipelined:
            st.ops["copy"].add(1, 0.0, 2 * n * dbl)
        if self.algo is not None:
            # CA recurrences: the schedule is the single source
            # (recurrence.reduction_schedule) -- fractional per-
            # iteration averages rounded to whole events
            from acg_tpu.recurrence import reduction_schedule
            sched = reduction_schedule(self.algo, False)
            nred = max(int(round(sched["allreduce_per_iteration"]
                                 * niter)), 1)
            st.ops["allreduce"].add(
                nred, 0.0, 8 * sched["allreduce_scalars"] * nred)
        else:
            st.ops["allreduce"].add(
                (1 if self.pipelined else 2) * niter, 0.0,
                8 * (1 if self.pipelined else 2) * niter)
        # local-read problems carry the allgathered total (summing subs
        # here would count only this controller's parts)
        halo_total = getattr(prob, "halo_send_total", None)
        if halo_total is None:
            halo_total = sum(int(s.halo.total_send) for s in prob.subs
                             if s.halo is not None)
        halo_bytes = halo_total * dbl
        nhalo = int(niter * spmv_eq) + 1
        st.ops["halo"].add(nhalo, 0.0, halo_bytes * nhalo)
        if self.precond_spec is not None:
            # the precond_apply census (jax_cg._account_precond's dist
            # twin): one apply per iteration + setup, cheby billing its
            # per-apply SpMVs -- and their halo exchanges, which are the
            # only preconditioner communication on this tier
            from acg_tpu import metrics as _metrics
            from acg_tpu import precond as precond_mod
            spec = self.precond_spec
            nappl = niter + 1
            per_fl = precond_mod.flops_per_apply(spec, n,
                                                 3.0 * prob.nnz_total)
            st.nflops += per_fl * nappl
            sb = precond_mod.state_bytes(self._mstate)
            per_b = precond_mod.bytes_per_apply(
                spec, n, dbl,
                prob.nnz_total * (mat_dbl + idx_b) + 2 * n * dbl, sb)
            nops = nappl * (spec.degree if spec.kind == "cheby" else 1)
            st.ops["precond"].add(nops, 0.0, int(per_b * nappl))
            st.ops["dot"].add(nappl, 0.0, 2 * n * dbl * nappl)
            if spec.kind == "cheby":
                st.ops["halo"].add(spec.degree * nappl, 0.0,
                                   halo_bytes * spec.degree * nappl)
            st.precond.update({"kind": str(spec), "applies": nappl,
                               "flops_per_apply": per_fl,
                               "state_bytes": sb})
            lams = getattr(self, "_precond_lams", None)
            if lams is not None:
                st.precond["lambda_min"] = lams[0]
                st.precond["lambda_max"] = lams[1]
            _metrics.record_precond(spec.kind, nops)

    def _host_fallback(self, b_global, crit, raise_on_divergence: bool,
                       host_result: bool):
        """The last recovery rung: re-solve on the distributed host
        oracle (HostDistCGSolver, same subdomain layout, f64 numpy) from
        the original b.  Only reachable on full single-controller builds
        -- restricted (multi-controller) problems hold other
        controllers' blocks as stubs, so the ladder ends at the raise
        there."""
        from acg_tpu import faults
        from acg_tpu.solvers.host_cg import HostDistCGSolver
        from acg_tpu.solvers.resilience import adopt_host_stats

        hs = HostDistCGSolver(self.problem.subs)
        with faults.suppressed():
            x = hs.solve(np.asarray(b_global, np.float64), criteria=crit,
                         raise_on_divergence=raise_on_divergence)
        adopt_host_stats(self.stats, hs.stats)
        if host_result:
            return x
        # callers expecting the stacked device layout still get it
        from acg_tpu.parallel.multihost import put_global
        return put_global(self.problem.scatter(x), sharding=self._sharding)

    # -- survivability tier: checkpoint-chunked solve ---------------------

    _ckpt_tier = "dist-cg"

    def _ckpt_program_for(self, fault):
        """The state_io chunk program: fault-armed compiles are
        solve-local (static spec changes per chunk as the injector
        shifts); the pristine one is cached."""
        if fault is not None:
            return self._compile(fault=fault, state_io=True)
        prog = getattr(self, "_ckpt_program", None)
        if prog is None:
            prog = self._ckpt_program = self._compile(state_io=True)
        return prog

    def _solve_ckpt(self, b_global, x0=None, criteria=None,
                    raise_on_divergence: bool = True, warmup: int = 0,
                    host_result: bool = True):
        """Checkpoint-armed solve over the mesh (acg_tpu.checkpoint):
        the UNCHANGED SPMD recurrence dispatched in host chunks of at
        most ``ckpt.every`` iterations with the full loop carry
        threaded through (``state_io``), every per-part leaf gathered
        host-side and committed under ONE agreed sequence number
        (checkpoint.agree_seq) so all ranks hold the same iteration,
        and breakdowns answered by the rollback rung before the
        restart/fallback ladder.  The carry continues the Krylov
        recurrence exactly, so the chunked trajectory is
        iteration-identical to solve()'s (tests/test_checkpoint.py);
        snapshot time is billed to its own ``ckpt`` phase."""
        from acg_tpu import checkpoint as ckpt_mod
        from acg_tpu import faults, metrics, observatory, telemetry, \
            tracing
        from acg_tpu import health as health_mod
        from acg_tpu._platform import block_until_ready_works, device_sync
        from acg_tpu.solvers.resilience import RecoveryDriver

        cfg = self.ckpt
        crit = criteria or StoppingCriteria()
        st = self.stats
        st.criteria = crit
        prob = self.problem
        dtype = self._solve_dtype()
        sdt = acc_dtype(np.dtype(prob.vdtype))
        if crit.needs_diff:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "checkpointing supports residual criteria only: the "
                "diff criterion's dx scalar is not part of the "
                "snapshot carry")
        fault0 = faults.device_fault()
        if (fault0 is not None and fault0.site == "halo"
                and not prob.halo.has_ghosts):
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "halo fault injection needs a topology with ghost "
                "exchange; this problem has no halo (single part or "
                "fully decoupled partition)")
        if (fault0 is not None and fault0.site == "precond"
                and self.precond_spec is None):
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                "precond fault injection needs an armed preconditioner "
                "(--precond jacobi|bjacobi|cheby:K); this solve runs "
                "unpreconditioned CG")
        detect = self._detect(fault0)
        if fault0 is not None:
            telemetry.record_event(st, "fault-armed",
                                   f"{fault0.site}:{fault0.mode}"
                                   f"@{fault0.iteration}")
        t_xfer = time.perf_counter()
        with telemetry.annotate("transfer"):
            dev = self.device_args(b_global, x0)
            b, x0_dev, la, ga, sidx, gsrc, gval, scnt, rcnt = dev
        telemetry.add_timing(st, "transfer", time.perf_counter() - t_xfer)
        b_crc = ckpt_mod.vector_checksum(np.asarray(b_global))
        kwargs = dict(unbounded=crit.unbounded, needs_diff=False,
                      detect=detect)
        if self.precond_spec is not None:
            self._last_dev_args = dev
            kwargs["mstate"] = self._ensure_precond_state(dev)
        fixed = (la, ga, sidx, gsrc, gval, scnt, rcnt, b)
        hl = self.health_spec is not None
        tr = self.trace
        pc_kind = (str(self.precond_spec)
                   if self.precond_spec is not None else None)
        names = ckpt_mod.carry_names(self.pipelined,
                                     self.precond_spec is not None)
        ncore = len(names) - 1
        scalar = ckpt_mod.SCALAR_LEAVES
        put = functools.partial(put_global, sharding=self._sharding)
        solver_name = ("dist-cg-pipelined" if self.pipelined
                       else "dist-cg")

        def to_dev(arrs):
            """Host snapshot arrays -> placed carry leaves (vectors
            scattered over the mesh, scalars as plain device scalars)."""
            return tuple(
                jnp.asarray(arrs[nm], dtype=sdt) if nm in scalar
                else put(np.asarray(arrs[nm], dtype=dtype))
                for nm in names[1:])

        def to_host(x_st, core):
            arrs = {"x": np.asarray(get_global(x_st))}
            for nm, leaf in zip(names[1:], core):
                arrs[nm] = np.asarray(get_global(leaf) if nm not in scalar
                                      else leaf)
            return arrs

        def run(program, x_cur, atol, rtol, m, carry, k0):
            tols = jnp.asarray([atol, rtol, 0.0, 0.0], dtype=sdt)
            koff = jnp.int32(k0) if hl else None
            out = program(*fixed, x_cur, tols, jnp.int32(m),
                          carry=carry, k_offset=koff, **kwargs)
            core = out[-ncore:]
            ring = out[9] if tr else None
            aud = out[9 + (1 if tr else 0)] if hl else None
            return out[:9], ring, aud, core

        # -- resume reconstruction ------------------------------------
        consumed = 0          # trajectory iterations (incl. pre-crash)
        executed = 0          # iterations THIS process actually ran
        resumed_from = None
        carry = None
        x_cur = x0_dev
        abs_tol = None
        first_norms = None
        snap = cfg.resume
        repartitioned = None
        if snap is not None:
            ckpt_mod.validate_resume(
                snap, tier=self._ckpt_tier, pipelined=self.pipelined,
                precond=pc_kind, n=int(prob.n), dtype=dtype,
                b_crc=b_crc, nparts=int(prob.nparts),
                repartition=cfg.repartition)
            ckpt_mod.check_resume_env(snap, st)
            if cfg.repartition:
                # shape-portable resume: reassemble the stored carry
                # into global row order via the permutation sidecar,
                # then RE-SLICE it onto THIS problem's partition (the
                # halo plans and preconditioner state were already
                # rebuilt for this mesh at solver setup) -- the Krylov
                # recurrence continues with the same global state, up
                # to dot-product re-association across the new layout
                snap, repartitioned = ckpt_mod.apply_repartition(
                    snap, tier=self._ckpt_tier,
                    nparts=int(prob.nparts), stats=st,
                    precond_spec=self.precond_spec)
                arrs_g = {}
                for nm, a in snap.arrays.items():
                    a = np.asarray(a)
                    arrs_g[nm] = (a if nm in scalar or a.ndim == 0
                                  else prob.scatter(a, dtype=a.dtype))
                snap = ckpt_mod.SolverSnapshot(meta=snap.meta,
                                               arrays=arrs_g)
            consumed = snap.iteration
            resumed_from = consumed
            sm = snap.meta
            abs_tol = float(sm["abs_tol"])
            first_norms = (float(sm["bnrm2"]), float(sm["x0nrm2"]),
                           float(sm["r0nrm2"]))
            x_cur = put(np.asarray(snap.arrays["x"], dtype=dtype))
            carry = to_dev(snap.arrays)
            metrics.record_resume()
            telemetry.record_event(
                st, "resume",
                f"resumed from snapshot at iteration {consumed}")
            sys.stderr.write(f"acg-tpu: {self._ckpt_tier}: resumed "
                             f"from snapshot at iteration {consumed}\n")
        last_snap = ((consumed, dict(snap.arrays))
                     if snap is not None else None)

        driver = RecoveryDriver(self.recovery, st, self._ckpt_tier)
        program = self._ckpt_program_for(fault0)
        block_until_ready_works()
        if warmup > 0:
            t_w = time.perf_counter()
            with telemetry.annotate("compile"):
                device_sync(run(program, x_cur, 0.0, 0.0, 0, carry,
                                consumed)[0][0])
            telemetry.add_timing(st, "compile",
                                 time.perf_counter() - t_w)

        def agreed_chunk(m: int) -> int:
            """The wall-clock cadence sizes chunks from a LOCALLY
            measured s/iteration; multi-controller, every rank must
            dispatch the SPMD program with the SAME iteration cap (a
            mismatched ``m`` desynchronises the in-loop collectives
            and agree_seq's iteration agreement).  All ranks gather
            their proposals and take the minimum -- the slowest
            rank's loss window stays the bound.  --ckpt-every is
            static and identical everywhere: no gather."""
            if cfg.secs <= 0 or jax.process_count() == 1:
                return m
            from acg_tpu.parallel.erragree import allgather_blobs
            got = allgather_blobs(str(int(m)), tag="ckpt-chunk")
            return max(1, min(int(g) for g in got))

        unbounded = crit.unbounded
        fault = fault0
        seq = 0
        nsnaps = 0
        ck_secs = 0.0
        rate = None
        aud_fresh = True
        gap_tripped = False
        res = None
        t0 = time.perf_counter()
        with telemetry.annotate("solve"):
            while True:
                remaining = crit.maxits - consumed
                if remaining <= 0:
                    break
                m = agreed_chunk(min(cfg.chunk_for(rate), remaining))
                chunk_fault = (fault.shift(executed)
                               if fault is not None else None)
                program = self._ckpt_program_for(chunk_fault)
                t_chunk = time.time()
                if abs_tol is None:
                    res, tbuf, aud, core = run(
                        program, x_cur, crit.residual_atol,
                        crit.residual_rtol, m, carry, consumed)
                else:
                    # later chunks keep the FIRST attempt's absolute
                    # target (never re-baseline rtol)
                    res, tbuf, aud, core = run(
                        program, x_cur, abs_tol, 0.0, m, carry,
                        consumed)
                device_sync(res[0])
                t_end = time.time()
                k_chunk = int(res[1])
                if k_chunk > 0:
                    # measured s/iteration sizes the next chunk under
                    # the wall-clock cadence (cfg.chunk_for)
                    rate = (t_end - t_chunk) / k_chunk
                # timeline tier: one span per chunked dispatch, named
                # by its trajectory window (no-op disarmed)
                tracing.record_span(
                    f"chunk k{consumed}..{consumed + k_chunk}",
                    t_chunk, t_end, cat="chunk",
                    k_offset=consumed, iterations=k_chunk)
                consumed += k_chunk
                executed += k_chunk
                if first_norms is None:
                    first_norms = (float(res[4]), float(res[5]),
                                   float(res[3]))
                    abs_tol = max(crit.residual_atol,
                                  crit.residual_rtol * first_norms[2])
                if tr:
                    st.trace = self.last_trace = \
                        telemetry.ConvergenceTrace.from_ring(
                            np.asarray(tbuf), k_chunk,
                            solver=solver_name,
                            offset=consumed - k_chunk)
                # live-observatory tier: real mid-solve sample from the
                # per-chunk carry return (no-op disarmed; host-side)
                observatory.note_chunk(
                    self._ckpt_tier, consumed, float(res[2]),
                    abs_tol=abs_tol,
                    trace=(st.trace if tr else None),
                    rtol=crit.residual_rtol)
                if hl and aud is not None:
                    gap_tripped = health_mod.note_audit(
                        st, np.asarray(aud), self.health_spec,
                        self._ckpt_tier, fresh=aud_fresh)
                    aud_fresh = False
                if detect and bool(res[8]):
                    if tr:
                        driver.log_trace_window(st.trace)
                    if (gap_tripped
                            and self.health_spec.action == "abort"):
                        st.tsolve += time.perf_counter() - t0 - ck_secs
                        st.converged = False
                        raise BreakdownError(
                            f"{self._ckpt_tier}: true-residual gap "
                            f"{st.health.get('gap_max', 0.0):.3e} "
                            f"exceeds threshold "
                            f"{self.health_spec.threshold:g} at "
                            f"iteration {consumed} (--on-gap abort)")
                    driver.note_breakdown(consumed)
                    # `fault` stays in the TRAJECTORY frame (the
                    # per-dispatch shift rebases it): vanish a fired
                    # fault instead of rebasing, which would make the
                    # dispatch shift double-subtract a pending one
                    if (fault is not None and fault.device_site
                            and fault.iteration <= executed):
                        fault = None
                    # FIRST RUNG: roll the carry back to the last
                    # agreed snapshot (exact pre-corruption Krylov
                    # state; the restart budget is untouched)
                    if (last_snap is not None
                            and driver.on_rollback(consumed,
                                                   last_snap[0])):
                        arrs = last_snap[1]
                        x_cur = put(np.asarray(arrs["x"], dtype=dtype))
                        carry = to_dev(arrs)
                        consumed = last_snap[0]
                        continue
                    # second rung: restart from the recomputed true
                    # residual (carry=None re-enters the setup path)
                    if driver.on_breakdown(consumed, noted=True):
                        x_next = res[0]
                        if not bool(jnp.isfinite(x_next).all()):
                            driver.record("iterate non-finite; "
                                          "restarting from the "
                                          "initial guess")
                            x_next = x0_dev
                        if self.precond_spec is not None:
                            from acg_tpu.precond import refresh_state
                            if refresh_state(self, driver):
                                kwargs["mstate"] = self._mstate
                        x_cur = x_next
                        carry = None
                        continue
                    pol = self.recovery
                    can_host = (pol is not None and pol.fallback_host
                                and prob.owned_parts is None
                                and all(s.A_local is not None
                                        for s in prob.subs))
                    if can_host:
                        driver.on_fallback("fallback: distributed host "
                                           "reference solver")
                        st.tsolve += time.perf_counter() - t0 - ck_secs
                        return self._host_fallback(
                            b_global, crit, raise_on_divergence,
                            host_result)
                    st.tsolve += time.perf_counter() - t0 - ck_secs
                    st.converged = False
                    raise driver.give_up(
                        consumed, float(res[2]),
                        snapshot=cfg.path if nsnaps else None)
                finished = (consumed >= crit.maxits if unbounded
                            else bool(res[7]))
                x_cur = res[0]
                carry = core
                if cfg.path is not None and not finished:
                    t_ck = time.perf_counter()
                    arrs = to_host(x_cur, core)
                    seq += 1
                    meta = {
                        "tier": self._ckpt_tier,
                        "pipelined": bool(self.pipelined),
                        "precond": pc_kind,
                        "n": int(prob.n),
                        "nparts": int(prob.nparts),
                        "dtype": str(np.dtype(dtype)),
                        "iteration": consumed,
                        "seq": seq,
                        "abs_tol": float(abs_tol),
                        "bnrm2": first_norms[0],
                        "x0nrm2": first_norms[1],
                        "r0nrm2": first_norms[2],
                        "b_crc": b_crc,
                        "fault": (str(faults.active_fault())
                                  if faults.active_fault() is not None
                                  else None),
                        "trace_tail": ckpt_mod.trace_tail(
                            st.trace if tr else None),
                    }
                    rp = prob.row_permutation()
                    if rp is not None:
                        # the shape-portable sidecar: global row ids
                        # in stacked slot order + per-part row counts
                        # let --resume-repartition reassemble this
                        # carry onto ANY partition (or the single-
                        # device/host tiers)
                        arrs["_rowperm"] = rp
                        meta["part_rows"] = prob.part_rows()
                    # ONE agreed sequence number across controllers
                    # before anything touches disk; the primary writes
                    ckpt_mod.agree_seq(seq, consumed)
                    if jax.process_index() == 0:
                        nbytes = ckpt_mod.save_snapshot(cfg.path, meta,
                                                        arrs)
                    else:
                        nbytes = 0
                    dt = time.perf_counter() - t_ck
                    ck_secs += dt
                    telemetry.add_timing(st, "ckpt", dt)
                    metrics.record_snapshot(nbytes, dt)
                    nsnaps += 1
                    last_snap = (consumed, arrs)
                    # crash:exit models preemption BETWEEN iterations,
                    # after the snapshot committed
                    faults.maybe_crash(consumed - k_chunk, consumed)
                if finished:
                    break
        if res is None:
            raise AcgError(
                ErrorCode.INVALID_VALUE,
                f"snapshot iteration {consumed} already meets the "
                f"iteration cap {crit.maxits}; raise --max-iterations "
                f"to continue this solve")
        t_solve = time.perf_counter() - t0 - ck_secs
        st.tsolve += t_solve
        telemetry.add_timing(st, "solve", t_solve)
        st.nsolves += 1
        st.niterations = executed
        st.ntotaliterations += executed
        st.bnrm2, st.x0nrm2, st.r0nrm2 = first_norms
        st.rnrm2 = float(res[2])
        st.dxnrm2 = float(res[6])
        st.converged = bool(res[7]) or crit.unbounded
        st.ckpt = {
            "path": cfg.path,
            "every": int(cfg.every),
            "snapshots": nsnaps,
            "iteration": consumed,
            "rollbacks": driver.rollbacks,
        }
        if cfg.secs > 0:
            st.ckpt["secs"] = float(cfg.secs)
        if resumed_from is not None:
            st.ckpt["resumed_from"] = resumed_from
        if repartitioned is not None:
            st.ckpt["repartitioned_from"] = repartitioned
        metrics.record_solve(t_solve, executed, st.converged,
                             solver=solver_name)
        metrics.observe_solver_comm(self, executed)
        self._account_ops(st, executed)
        x_st = res[0]
        if host_result:
            x = prob.gather(get_global(x_st))
            st.fexcept_arrays = [x]
        else:
            x = x_st
            has_nan = bool(jnp.isnan(x_st).any())
            has_inf = bool(jnp.isinf(x_st).any())
            st.fexcept_arrays = [np.asarray([np.nan if has_nan else 0.0,
                                             np.inf if has_inf
                                             else 0.0])]
        if not st.converged and raise_on_divergence:
            raise NotConvergedError(
                f"{executed} iterations, residual {st.rnrm2:.3e}")
        return x
