from acg_tpu.parallel.mesh import solve_mesh  # noqa: F401
from acg_tpu.parallel.dist import DistributedProblem, DistCGSolver  # noqa: F401
