"""Sharded on-device stencil assembly + solve: the north-star route.

The reference reaches large problems by having the root rank read/build
the matrix and scatter per-rank subgraphs over MPI
(``acg/graph.c:1529-1897``, ``acg/mtxfile.h:997-1087``).  For stencil
matrices on TPU both halves of that design are unnecessary:

* **Assembly** is a jitted computation from iotas placed directly into
  each device's HBM shard (``jit`` with sharded ``out_shardings``): no
  host matrix, no scatter, no transfer -- each controller materialises
  only its local shards, so host memory is O(1) and device memory
  O(N/P) per chip.  This is the multi-chip extension of
  :func:`acg_tpu.io.generators.poisson_dia_device`.
* **The halo exchange is derived, not planned.**  The solve programs run
  the cyclic-shift SpMV (:func:`acg_tpu.ops.spmv.dia_mv_roll`); XLA's
  SPMD partitioner compiles each static shift of the sharded vector into
  boundary ``collective-permute``s over ICI -- exactly the neighbour
  halo the reference builds by hand (``acg/halo.c``), with zero
  all-gathers (asserted in tests at the HLO level).  Dot products psum
  automatically the same way.

Because every input is born sharded, the identical code path runs
single-chip, multi-chip single-controller, and multi-controller
(``--multihost``): under a multi-process runtime the same jitted program
executes over the global mesh and each process only ever touches its
addressable shards.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from acg_tpu.ops.precision import df_add, two_prod
from acg_tpu.ops.spmv import DiaMatrix, acc_dtype
from acg_tpu.parallel.mesh import PARTS_AXIS, solve_mesh
from acg_tpu.solvers.jax_cg import JaxCGSolver


def dia_mv_roll_df(planes, offsets, xh, xl):
    """``y = A x`` in DOUBLE-FLOAT (df64) arithmetic over the roll
    formulation: x rides as an (hi, lo) f32 pair, every product uses the
    Dekker two-product and every accumulation the Knuth two-sum, so y
    carries ~48 mantissa bits -- f64-class -- while every array and op
    stays hardware f32 (and shards/partitions exactly like
    :func:`acg_tpu.ops.spmv.dia_mv_roll`: the rolls still compile to
    boundary collective-permutes).

    Stencil plane values (-1, 2d) are exactly representable in
    f32/bf16, so promoting planes to f32 here is LOSSLESS -- which is
    what makes a df64 residual over the same on-device planes an
    f64-grade oracle (round-3 verdict item 3; the role of the
    reference's strictly-f64 arithmetic, ``comm.h:180-183``).
    """
    sdt = jnp.float32
    yh = jnp.zeros_like(xh, dtype=sdt)
    yl = jnp.zeros_like(xh, dtype=sdt)
    for plane, off in zip(planes, offsets):
        v = plane.astype(sdt)
        ph, pe = two_prod(v, jnp.roll(xh, -off).astype(sdt))
        pe = pe + v * jnp.roll(xl, -off).astype(sdt)
        yh, yl = df_add((yh, yl), (ph, pe))
    return yh, yl


def _halo_sizes(offsets, nloc: int):
    """(Lh, Rh): per-shard halo widths for the pallas-roll SpMV, rounded
    up to the kernel row tile when that keeps the fast route's
    tile-divisibility (the window length ``nloc + Lh + Rh`` then stays a
    tile multiple whenever ``nloc`` is); tiny shards keep the exact band
    (their windows take the kernel's fallback routes anyway)."""
    from acg_tpu.ops.pallas_kernels import TILE

    L = max(0, -min(offsets))
    R = max(0, max(offsets))
    Lh = L + (-L) % TILE
    Rh = R + (-R) % TILE
    if Lh > nloc or Rh > nloc:
        Lh, Rh = L, R
    return Lh, Rh


class PallasRollSpmv:
    """Sharded DIA SpMV running the clustered Pallas kernel PER SHARD
    under ``shard_map``, with the halo exchanged explicitly by
    ``lax.ppermute`` (round-4 verdict item 7: bring the kernel tier that
    wins single-chip to the sharded gen-direct route).

    The square kernel (:func:`acg_tpu.ops.pallas_kernels.dia_spmv`) is
    reused UNCHANGED: each shard's planes are stored pre-PADDED to the
    halo'd window length (``[0]*Lh + plane_loc + [0]*Rh`` --
    :func:`sharded_poisson_dia_padded`, built once at assembly), so the
    kernel's ``y[i] = sum_d plane[d][i] * x[i + off_d]`` over the window
    ``[halo_L | x_loc | halo_R]`` produces exactly the local rows at
    window positions ``[Lh, Lh + nloc)`` and structural zeros elsewhere
    (discarded by the slice).  Edge shards zero-fill their missing halo
    -- correctness-neutral for the same structural-zero reason as the
    roll formulation's wraparound.

    Instances are used as the ``kernels`` static argument of the jitted
    solve programs (identity-hashed: one compile per solver)."""

    name = "pallas-roll"

    def __init__(self, mesh: Mesh, nloc: int, Lh: int, Rh: int,
                 offsets, interpret: bool = False):
        self.mesh = mesh
        self.nloc, self.Lh, self.Rh = int(nloc), int(Lh), int(Rh)
        self.offsets = tuple(int(o) for o in offsets)
        self.interpret = bool(interpret)
        nparts = int(np.prod(tuple(mesh.shape.values())))
        self._fwd = [(i, i + 1) for i in range(nparts - 1)]
        self._bwd = [(i + 1, i) for i in range(nparts - 1)]

    def __call__(self, A, x):
        from acg_tpu.ops.pallas_kernels import dia_spmv

        nloc, Lh, Rh = self.nloc, self.Lh, self.Rh
        offsets = self.offsets
        interpret = self.interpret

        def shard(planes, xl):
            parts = []
            if Lh:
                # left halo = left neighbour's TAIL; shard 0 (no
                # source pair) receives ppermute's zero fill
                parts.append(jax.lax.ppermute(xl[nloc - Lh:], PARTS_AXIS,
                                              self._fwd))
            parts.append(xl)
            if Rh:
                parts.append(jax.lax.ppermute(xl[:Rh], PARTS_AXIS,
                                              self._bwd))
            xwin = jnp.concatenate(parts) if len(parts) > 1 else xl
            y = dia_spmv(planes, offsets, xwin, interpret=interpret)
            return jax.lax.slice(y, (Lh,), (Lh + nloc,))

        spec = P(PARTS_AXIS)
        from acg_tpu._platform import shard_map as _shard_map
        return _shard_map(shard, mesh=self.mesh,
                          in_specs=(spec, spec), out_specs=spec)(A.data, x)


def sharded_poisson_dia_padded(n: int, dim: int, mesh: Mesh, nloc: int,
                               Lh: int, Rh: int, dtype=jnp.float32):
    """Poisson DIA planes in the PER-SHARD-PADDED layout consumed by
    :class:`PallasRollSpmv`: each plane is a ``(nparts * nwin,)`` array
    (``nwin = Lh + nloc + Rh``) sharded over the mesh, whose shard ``s``
    holds ``[0]*Lh + plane[s*nloc : (s+1)*nloc] + [0]*Rh``.  Pure iota
    arithmetic like :func:`sharded_poisson_dia` -- no host data, no
    communication, and the ~(Lh+Rh)/nloc extra zeros are built once at
    assembly (not per SpMV)."""
    nparts = int(np.prod(tuple(mesh.shape.values())))
    nwin = Lh + nloc + Rh
    N = n ** dim
    sh = NamedSharding(mesh, P(PARTS_AXIS))

    @jax.jit
    def build():
        g = jax.lax.iota(jnp.int32, nparts * nwin)
        s = g // nwin
        j = g % nwin - Lh               # local row, negative in the halo
        row = jnp.clip(s * nloc + j, 0, N - 1)
        valid = (j >= 0) & (j < nloc)
        planes = []
        for a in range(dim):
            stride = n ** a
            coord = (row // stride) % n
            planes.append(jnp.where(valid & (coord > 0),
                                    -1.0, 0.0).astype(dtype))
            planes.append(jnp.where(valid & (coord < n - 1),
                                    -1.0, 0.0).astype(dtype))
        planes.append(jnp.where(valid, float(2 * dim), 0.0).astype(dtype))
        return [jax.lax.with_sharding_constraint(p, sh) for p in planes]

    offsets = [s for a in range(dim) for s in (-(n ** a), n ** a)] + [0]
    order = np.argsort(offsets)
    planes = build()
    return ([planes[i] for i in order],
            tuple(int(offsets[i]) for i in order), nwin)


def sharded_poisson_dia(n: int, dim: int, mesh: Mesh, dtype=jnp.float32):
    """Poisson DIA planes assembled on device, sharded over ``mesh``.

    Returns ``(planes, offsets, N)``; each plane is an (N,) array laid
    out ``PartitionSpec(parts)`` over the mesh.  The computation is pure
    iota arithmetic, so XLA materialises each shard on its own device
    with no communication and no host data.
    """
    N = n ** dim
    sh = NamedSharding(mesh, P(PARTS_AXIS))

    @jax.jit
    def build():
        planes = []
        for a in range(dim):
            stride = n ** a
            coord = (jax.lax.iota(jnp.int32, N) // stride) % n
            planes.append(jnp.where(coord > 0, -1.0, 0.0).astype(dtype))
            planes.append(jnp.where(coord < n - 1, -1.0, 0.0).astype(dtype))
        planes.append(jnp.full((N,), float(2 * dim), dtype=dtype))
        return [jax.lax.with_sharding_constraint(p, sh) for p in planes]

    offsets = [s for a in range(dim) for s in (-(n ** a), n ** a)] + [0]
    order = np.argsort(offsets)
    planes = build()
    return ([planes[i] for i in order],
            tuple(int(offsets[i]) for i in order), N)


class ShardedDiaCGSolver(JaxCGSolver):
    """CG over a mesh-sharded square DIA matrix.

    A thin specialisation of :class:`JaxCGSolver`: the solve programs
    are unchanged -- input sharding alone turns them into SPMD programs
    (the role of ``acgsolvercuda_solvempi``'s explicit communicator
    plumbing, ``cgcuda.c:403-1143``, is played by GSPMD propagation).
    The SpMV is pinned to the roll formulation, whose shifts partition
    into neighbour collective-permutes (``kernels="xla-roll"``).
    """

    # snapshots from this tier name their own provenance: the sharded
    # roll programs' carry is the global-vector layout (JaxCGSolver's),
    # but a resume must re-enter the SAME SpMV selection
    _ckpt_tier = "sharded-dia"

    def __init__(self, A: DiaMatrix, mesh: Mesh | None = None,
                 pipelined: bool = False, precise_dots: bool = False,
                 vector_dtype=None, stencil: tuple[int, int] | None = None,
                 replace_every: int = 0, replace_restart: bool = True,
                 recovery=None, trace: int = 0, progress: int = 0,
                 precond=None, health=None, ckpt=None, algorithm=None):
        if A.ncols_padded != A.nrows:
            raise ValueError("sharded DIA solve needs a square matrix")
        # replace_every (the sound bf16 tier, _cg_replaced_program)
        # composes with the roll SpMV unchanged: its inner bf16 and
        # replacement f32 SpMVs shard into the same boundary
        # collective-permutes as every other program here (round-4
        # verdict item 1 -- the half-traffic accuracy contract on the
        # north-star path; ref ``comm.h:180-183``, ``cgcuda.c:1941``).
        # trace/progress (the telemetry tier) ride the same programs:
        # the CG scalars are global reductions, so the recorded ring is
        # replicated by GSPMD exactly like the result scalars
        # precond (acg_tpu.precond) rides the inherited programs
        # unchanged: the jacobi diagonal is the sharded offset-0 plane,
        # bjacobi's block extraction shards by block row, and the cheby
        # apply's rolls partition into the same boundary collective-
        # permutes as every other SpMV of the loop
        # health (acg_tpu.health) likewise: the audit's b - A x runs
        # the same roll SpMV (boundary collective-permutes under the
        # SPMD partitioner), its norm psums through sharding
        # propagation like the CG scalars, and the audit vector comes
        # back replicated exactly like the result scalars
        # ckpt (acg_tpu.checkpoint) rides the inherited chunk driver:
        # the roll programs' state_io carry shards into the same
        # boundary collective-permutes as every other output, and the
        # snapshot stores the gathered global vectors
        # the CA recurrences (acg_tpu.recurrence: sstep:S / p(l)-CG)
        # likewise ride the inherited builder programs: the basis
        # products and window SpMVs are this tier's roll SpMV, the
        # Gram/window matmuls psum through sharding propagation like
        # the CG scalars
        super().__init__(A, pipelined=pipelined, precise_dots=precise_dots,
                         kernels="xla-roll", vector_dtype=vector_dtype,
                         replace_every=replace_every,
                         replace_restart=replace_restart,
                         recovery=recovery, trace=trace, progress=progress,
                         precond=precond, health=health, ckpt=ckpt,
                         algorithm=algorithm)
        self.mesh = mesh if mesh is not None else solve_mesh()
        # fault-injection diagnosis hook (JaxCGSolver.solve): this tier
        # is multi-part but still cannot honour part= targeting
        self._fault_nparts = int(self.mesh.devices.size)
        self.sharding = NamedSharding(self.mesh, P(PARTS_AXIS))
        # (n, dim) of the generating stencil, when known: enables the
        # independent analytic spot check of manufactured systems
        self.stencil = stencil

    def use_pallas_roll(self, n: int, dim: int) -> None:
        """Switch the solve programs to the per-shard Pallas kernel tier
        (:class:`PallasRollSpmv`): validates the shard geometry, then
        assembles the per-shard-padded plane twin
        (:func:`sharded_poisson_dia_padded`) the windowed kernel
        consumes.  ``self.A`` keeps the clean (N,) planes for every
        non-program consumer (manufactured systems, df64 refinement
        residuals, the analytic spot check)."""
        nparts = int(np.prod(tuple(self.mesh.shape.values())))
        N = self.A.nrows
        if N % nparts:
            raise ValueError(
                f"pallas-roll needs evenly sharded rows "
                f"(N={N} % nparts={nparts} != 0); use kernels='xla-roll'")
        nloc = N // nparts
        Lh, Rh = _halo_sizes(self.A.offsets, nloc)
        if max(Lh, Rh) > nloc:
            # band wider than a shard: the single-neighbour ppermute
            # halo cannot reach offset targets two shards away
            raise ValueError(
                f"pallas-roll halo ({max(Lh, Rh)}) exceeds the shard "
                f"size ({nloc}); use kernels='xla-roll'")
        padded, off2, _nwin = sharded_poisson_dia_padded(
            n, dim, self.mesh, nloc, Lh, Rh, dtype=self.A.dtype)
        if off2 != self.A.offsets:
            raise ValueError(f"padded assembly offsets {off2} disagree "
                             f"with the solver's {self.A.offsets}")
        interpret = self.mesh.devices.flat[0].platform != "tpu"
        self.kernels = PallasRollSpmv(self.mesh, nloc, Lh, Rh,
                                      self.A.offsets, interpret=interpret)
        self._A_program = DiaMatrix(data=tuple(padded),
                                    offsets=self.A.offsets,
                                    nrows=N, ncols_padded=N)

    def comm_profile(self) -> dict:
        """Static per-iteration communication ledger for the sharded
        roll tiers (the perfmodel tier).  The halo here is DERIVED, not
        planned: under ``xla-roll`` each nonzero offset's cyclic shift
        partitions into a boundary ``collective-permute`` of
        ``min(|offset|, nloc)`` elements per shard (offsets wider than a
        shard hop multiple neighbours); the ``pallas-roll`` tier's
        explicit ppermute halo moves its padded ``Lh + Rh`` window
        edges to adjacent shards.  The CG scalars psum exactly like the
        explicit distributed path's (classic 2 x 1 scalar, pipelined 1
        fused x 2; compensated dots double each payload)."""
        P = int(self.mesh.devices.size)
        N = int(self.A.nrows)
        nloc = -(-N // P) if P else N
        vdt = (jnp.dtype(self.vector_dtype)
               if self.vector_dtype is not None else
               jnp.dtype(self.A.dtype))
        if self.replace_every:
            # the inner recurrences (and so the per-iteration halo
            # payload) ride bf16 under the replacement tier
            vdt = jnp.dtype(jnp.bfloat16)
        dbl = int(np.dtype(vdt).itemsize)
        sdl = int(np.dtype(acc_dtype(vdt)).itemsize)
        pallas = isinstance(self.kernels, PallasRollSpmv)
        if P <= 1:
            per_shard, max_hops, nexch = 0, 0, 0
        elif pallas:
            per_shard = int(self.kernels.Lh + self.kernels.Rh)
            max_hops = 1
            # one explicit ppermute per populated halo side
            nexch = int(bool(self.kernels.Lh)) + int(bool(self.kernels.Rh))
        else:
            offs = [abs(int(o)) for o in self.A.offsets if o]
            per_shard = sum(min(o, nloc) for o in offs)
            max_hops = max((-(-o // nloc) for o in offs), default=0)
            # each nonzero offset's cyclic shift partitions into its OWN
            # boundary collective-permute (unlike the explicit path's
            # single packed all_to_all) -- per-exchange latency pricing
            # must see every one of them
            nexch = len(offs)
        nred = 1 if self.pipelined else 2
        scal = ((2 if self.pipelined else 1)
                * (2 if self.precise_dots else 1))
        algo_led = {}
        if self.algo is not None:
            # CA reclassification (the explicit dist tier's rule): the
            # reduction schedule is the recurrence's own declaration
            from acg_tpu.recurrence import reduction_schedule
            sched = reduction_schedule(self.algo, False)
            nred = sched["allreduce_per_iteration"]
            scal = sched["allreduce_scalars"]
            nexch = nexch * sched["spmv_per_iteration"]
            per_shard = per_shard * sched["spmv_per_iteration"]
            algo_led = {"algorithm": str(self.algo)}
            for extra_key in ("iterations_per_reduction",
                              "reduction_latency_hidden"):
                if extra_key in sched:
                    algo_led[extra_key] = sched[extra_key]
        precond_led = {}
        ar_bytes = None
        if self.precond_spec is not None:
            # PCG reclassification (the explicit dist tier's rule):
            # cheby multiplies the derived-halo pattern by its degree,
            # the PCG scalar widens the fused reductions
            from acg_tpu.precond import comm_contribution
            pc = comm_contribution(self.precond_spec)
            extra = int(pc.get("halo_spmv_equivalents_per_apply", 0))
            nexch = nexch * (1 + extra)
            per_shard = per_shard * (1 + extra)
            # widest payload in the scalars field; BYTES bill the true
            # per-iteration total (both PCG loops move 3 scalars --
            # classic: 1 + the 2-scalar fusion)
            scal = ((3 if self.pipelined else 2)
                    * (2 if self.precise_dots else 1))
            ar_bytes = 3 * (2 if self.precise_dots else 1) * sdl
            precond_led = {"precond": pc}
        return {
            "transport": ("pallas-roll/ppermute" if pallas
                          else "xla-roll/collective-permute"),
            **algo_led,
            "nparts": P,
            "mesh_shape": {str(k): int(v)
                           for k, v in dict(self.mesh.shape).items()},
            "halo_exchanges_per_iteration": nexch,
            "halo_bytes_per_iteration": int(per_shard * P * dbl),
            "halo_bytes_per_shard": int(per_shard * dbl),
            "allreduce_per_iteration": (nred if self.algo is not None
                                        else int(nred)),
            "allreduce_scalars": int(scal),
            "allreduce_bytes_per_iteration": int(
                nred * scal * sdl if ar_bytes is None else ar_bytes),
            "max_hops": int(max_hops),
            **precond_led,
        }

    def ones_b(self, dtype=None) -> jax.Array:
        """A sharded all-ones right-hand side (the CLI default b)."""
        dtype = dtype or self.vector_dtype or self.A.dtype
        return jax.jit(
            lambda: jnp.ones(self.A.nrows, dtype=dtype),
            out_shardings=self.sharding)()

    def manufactured(self, seed: int = 42):
        """``(xsol, b)`` on device, sharded: random unit-norm solution
        and ``b = A xsol`` via the same sharded SpMV (the role of the
        reference's manufactured-solution setup,
        ``cuda/acg-cuda.c:1969-2140``; the independent-oracle role of
        its host SpMV is covered at small sizes by the CPU-mesh tests,
        which check this b against scipy)."""
        from acg_tpu.ops.spmv import dia_mv_roll

        dtype = self.vector_dtype or self.A.dtype
        if self.replace_every:
            # the replacement tier's OUTER iteration owns b/x in f32
            # (solve() casts either way); manufacturing b in bf16 here
            # would bake a u_bf16 backward error into every residual the
            # replacement recomputes -- and fail the analytic spot check
            dtype = jnp.float32
        sdt = jnp.promote_types(dtype, jnp.float32)
        offsets = self.A.offsets
        nrows = self.A.nrows
        sharding = self.sharding

        # planes ride as ARGUMENTS: a jit may not close over arrays that
        # span other controllers' devices (multi-controller rule)
        @jax.jit
        def build(key, planes):
            xsol = jax.random.normal(key, (nrows,), dtype=sdt)
            xsol = (xsol / jnp.linalg.norm(xsol)).astype(dtype)
            xsol = jax.lax.with_sharding_constraint(xsol, sharding)
            b = dia_mv_roll(planes, offsets, xsol)
            return xsol, b

        return build(jax.random.key(seed), self.A.data)

    def error_norms(self, x, xsol):
        """``(err0, err)``: initial and final solution error 2-norms
        (device-side; only scalars reach the host)."""
        sdt = jnp.promote_types(x.dtype, jnp.float32)
        err = float(jnp.linalg.norm((x - xsol).astype(sdt)))
        err0 = float(jnp.linalg.norm(xsol.astype(sdt)))
        return err0, err

    def manufactured_df(self, seed: int = 42):
        """``(xsol, (bh, bl))``: manufactured setup with b computed in
        DOUBLE-FLOAT -- required for f64-grade refinement targets (a b
        rounded to f32 caps the reachable error at ~1e-7 no matter how
        accurate the solver)."""
        offsets = self.A.offsets
        nrows = self.A.nrows
        sharding = self.sharding

        @jax.jit
        def build(key, planes):
            xsol = jax.random.normal(key, (nrows,), dtype=jnp.float32)
            xsol = xsol / jnp.linalg.norm(xsol)
            xsol = jax.lax.with_sharding_constraint(xsol, sharding)
            bh, bl = dia_mv_roll_df(planes, offsets, xsol,
                                    jnp.zeros_like(xsol))
            return xsol, bh, bl

        xsol, bh, bl = build(jax.random.key(seed), self.A.data)
        return xsol, (bh, bl)

    def solve_refined(self, b, criteria=None, inner_rtol: float = 1e-5,
                      warmup: int = 0, max_passes: int = 40,
                      inner_maxits: int | None = None):
        """Device-resident SHARDED iterative refinement: df64 outer
        residual (``dia_mv_roll_df`` over the same on-device planes --
        lossless promotion for stencil values), f32 inner CG solves,
        df64 solution accumulator.  Reaches f64-class solution error
        with no host matrix and no host vectors -- the sharded
        restatement of :class:`acg_tpu.solvers.refine.RefinedSolver`
        (round-3 verdict item 3; ref ``cg.h:136-149``,
        ``comm.h:180-183``).

        ``b`` may be an f32 array or an ``(bh, bl)`` df64 pair (use
        :meth:`manufactured_df` for f64-grade targets).  Returns the
        (hi, lo) solution pair; ``hi`` alone is the f32 view.
        """
        import time as _time

        from acg_tpu.solvers.stats import StoppingCriteria

        crit = criteria or StoppingCriteria()
        bh, bl = b if isinstance(b, tuple) else (
            jnp.asarray(b, jnp.float32), None)
        offsets = self.A.offsets
        sharding = self.sharding

        @jax.jit
        def residual(planes, bh, bl, xh, xl):
            ah, al = dia_mv_roll_df(planes, offsets, xh, xl)
            rh, rl = df_add((bh, bl if bl is not None
                             else jnp.zeros_like(bh)),
                            (-ah, -al))
            rh = jax.lax.with_sharding_constraint(rh, sharding)
            rl = jax.lax.with_sharding_constraint(rl, sharding)
            return rh, rl, jnp.linalg.norm(rh)

        @jax.jit
        def accumulate(xh, xl, d):
            hi, lo = df_add((xh, xl), (d, jnp.zeros_like(d)))
            return (jax.lax.with_sharding_constraint(hi, sharding),
                    jax.lax.with_sharding_constraint(lo, sharding))

        st = self.stats
        st.criteria = crit
        t0 = _time.perf_counter()
        zeros = jax.jit(lambda r: jnp.zeros_like(r),
                        out_shardings=sharding)(bh)
        xh, xl = zeros, zeros
        rh, rl, rnrm = residual(self.A.data, bh, bl, xh, xl)
        r0nrm = float(rnrm)
        st.r0nrm2 = r0nrm
        st.bnrm2 = r0nrm  # x0 = 0: r0 == b
        st.x0nrm2 = 0.0
        res_tol = max(crit.residual_atol, crit.residual_rtol * r0nrm)
        unbounded = res_tol <= 0
        total_inner = 0
        npasses = 0
        rnrm_f = r0nrm
        stalled = False
        converged = (not unbounded) and rnrm_f < res_tol
        while (not converged and not stalled and npasses < max_passes
               and total_inner < crit.maxits):
            budget = crit.maxits - total_inner
            # inner_maxits caps one pass's device program: at 512^3 a
            # budget-sized inner while_loop would outrun the tunnel's
            # ~25 s program watchdog (bench.MAX_PROGRAM_SECONDS notes)
            inner_crit = StoppingCriteria(
                maxits=min(inner_maxits or budget, budget),
                residual_rtol=inner_rtol)
            self.stats = SolverStats_inner = type(st)(unknowns=st.unknowns)
            try:
                d = super().solve(rh, criteria=inner_crit,
                                  raise_on_divergence=False,
                                  warmup=warmup, host_result=False)
            finally:
                inner_iters = self.stats.niterations
                self.stats = st
            warmup = 0
            xh_new, xl_new = accumulate(xh, xl, d)
            rh2, rl2, rnrm2_ = residual(self.A.data, bh, bl, xh_new, xl_new)
            rnrm_new = float(rnrm2_)
            npasses += 1
            total_inner += inner_iters
            # `not (new < old)` so a NaN residual (diverged inner solve)
            # also keeps the better previous iterate and stops
            if not (rnrm_new < rnrm_f):
                stalled = True
            else:
                xh, xl, rh, rl = xh_new, xl_new, rh2, rl2
                if rnrm_new >= 0.5 * rnrm_f:
                    stalled = True  # accuracy exhausted
                rnrm_f = rnrm_new
            converged = (not unbounded) and rnrm_f < res_tol
        if unbounded:
            converged = True
        st.tsolve += _time.perf_counter() - t0
        st.nsolves += 1
        st.nrefine = npasses
        st.niterations = total_inner
        st.ntotaliterations += total_inner
        st.rnrm2 = rnrm_f
        st.dxnrm2 = float("inf")
        st.converged = bool(converged)
        st.fexcept_arrays = [np.asarray([0.0])]
        if not converged:
            from acg_tpu.errors import NotConvergedError
            raise NotConvergedError(
                f"sharded refinement stalled after {npasses} passes "
                f"({total_inner} inner iterations), residual {rnrm_f:.3e}")
        return xh, xl

    def error_norms_df(self, xh, xl, xsol):
        """Solution error of a df64 iterate against an f32 xsol, without
        leaving df precision: ``|| (xh - xsol) + xl ||``."""
        @jax.jit
        def err(xh, xl, xsol):
            from acg_tpu.ops.precision import two_sum
            dh, dl = two_sum(xh, -xsol)
            d = dh + (dl + xl)
            return jnp.linalg.norm(d)

        return float(jnp.linalg.norm(xsol)), float(err(xh, xl, xsol))


def spot_check_manufactured(solver, xsol, b, nsample: int = 64,
                            seed: int = 0) -> float:
    """INDEPENDENT verification of the manufactured right-hand side:
    sample rows, recompute each b_i on the HOST in f64 from the analytic
    stencil (b_i = 2d x_i - sum of in-bounds axis neighbours), and
    return the max relative deviation from the device b.

    This de-circularises the large-scale oracle (round-3 verdict item
    5): the 512^3 error check otherwise shares ``dia_mv_roll`` between
    manufacturing b and solving, so a roll/sharding bug would cancel
    out.  Here nothing is shared -- host arithmetic, analytic stencil
    values, and only O(nsample * stencil) scalars cross the wire (the
    sampled restatement of the reference's independent host SpMV,
    ``cuda/acg-cuda.c:2115``).
    """
    n, dim = solver.stencil
    N = solver.A.nrows
    rng = np.random.default_rng(seed)
    rows = np.unique(rng.integers(0, N, size=nsample))
    offs = [s for a in range(dim) for s in (-(n ** a), n ** a)]
    need = [rows]
    valid = {}
    for off in offs:
        stride = abs(off)
        coord = (rows // stride) % n
        ok = coord > 0 if off < 0 else coord < n - 1
        valid[off] = ok
        need.append(np.where(ok, rows + off, rows))  # clamped when invalid
    need_idx = np.unique(np.concatenate(need))

    bh = b[0] if isinstance(b, tuple) else b
    # REPLICATED gather output: an unconstrained eager gather of a
    # sharded vector is not guaranteed fully addressable per process
    # under multi-controller runs -- exactly the scale this check is
    # meant to validate (round-4 advisor finding)
    gather = jax.jit(lambda v, i: v[i],
                     out_shardings=NamedSharding(solver.mesh, P()))
    xv = np.asarray(gather(xsol, jnp.asarray(need_idx)), dtype=np.float64)
    bv = np.asarray(gather(bh, jnp.asarray(rows)), dtype=np.float64)
    lut = {int(g): k for k, g in enumerate(need_idx)}
    xs = np.array([xv[lut[int(i)]] for i in rows])
    expect = 2.0 * dim * xs
    for off in offs:
        nb = np.array([xv[lut[int(i + off)]] if ok else 0.0
                       for i, ok in zip(rows, valid[off])])
        expect = expect - nb
    scale = float(np.max(np.abs(bv)) or 1.0)
    return float(np.max(np.abs(bv - expect)) / scale)


def build_sharded_poisson_solver(n: int, dim: int, nparts: int | None = None,
                                 dtype=jnp.float32, vector_dtype=None,
                                 pipelined: bool = False,
                                 precise_dots: bool = False,
                                 epsilon: float = 0.0,
                                 replace_every: int = 0,
                                 replace_restart: bool = True,
                                 kernels: str = "xla-roll",
                                 recovery=None, trace: int = 0,
                                 progress: int = 0, precond=None,
                                 health=None, ckpt=None, algorithm=None):
    """Assemble a sharded Poisson problem and its solver in one call
    (the gen-direct CLI path under ``--nparts``/``--multihost``).

    ``kernels="pallas-roll"`` runs the per-shard clustered Pallas SpMV
    with an explicit ppermute halo (:class:`PallasRollSpmv`) instead of
    the GSPMD-partitioned roll formulation; incompatible with
    ``epsilon`` (the padded assembly bakes the pure stencil)."""
    if kernels not in ("xla-roll", "pallas-roll"):
        raise ValueError(f"unknown sharded kernels choice {kernels!r} "
                         f"(xla-roll or pallas-roll)")
    mesh = solve_mesh(nparts)
    planes, offsets, N = sharded_poisson_dia(n, dim, mesh, dtype=dtype)
    if epsilon:
        if kernels == "pallas-roll":
            raise ValueError("kernels='pallas-roll' does not support "
                             "--epsilon (the padded assembly bakes the "
                             "pure stencil); use kernels='xla-roll'")
        d = offsets.index(0)
        sh = NamedSharding(mesh, P(PARTS_AXIS))
        planes = list(planes)
        planes[d] = jax.jit(
            lambda p: p + jnp.asarray(epsilon, p.dtype),
            out_shardings=sh)(planes[d])
    A = DiaMatrix(data=tuple(planes), offsets=offsets,
                  nrows=N, ncols_padded=N)
    solver = ShardedDiaCGSolver(A, mesh=mesh, pipelined=pipelined,
                                precise_dots=precise_dots,
                                vector_dtype=vector_dtype,
                                stencil=(n, dim) if not epsilon else None,
                                replace_every=replace_every,
                                replace_restart=replace_restart,
                                recovery=recovery, trace=trace,
                                progress=progress, precond=precond,
                                health=health, ckpt=ckpt,
                                algorithm=algorithm)
    if kernels == "pallas-roll":
        solver.use_pallas_roll(n, dim)
    return solver
