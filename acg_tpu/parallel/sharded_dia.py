"""Sharded on-device stencil assembly + solve: the north-star route.

The reference reaches large problems by having the root rank read/build
the matrix and scatter per-rank subgraphs over MPI
(``acg/graph.c:1529-1897``, ``acg/mtxfile.h:997-1087``).  For stencil
matrices on TPU both halves of that design are unnecessary:

* **Assembly** is a jitted computation from iotas placed directly into
  each device's HBM shard (``jit`` with sharded ``out_shardings``): no
  host matrix, no scatter, no transfer -- each controller materialises
  only its local shards, so host memory is O(1) and device memory
  O(N/P) per chip.  This is the multi-chip extension of
  :func:`acg_tpu.io.generators.poisson_dia_device`.
* **The halo exchange is derived, not planned.**  The solve programs run
  the cyclic-shift SpMV (:func:`acg_tpu.ops.spmv.dia_mv_roll`); XLA's
  SPMD partitioner compiles each static shift of the sharded vector into
  boundary ``collective-permute``s over ICI -- exactly the neighbour
  halo the reference builds by hand (``acg/halo.c``), with zero
  all-gathers (asserted in tests at the HLO level).  Dot products psum
  automatically the same way.

Because every input is born sharded, the identical code path runs
single-chip, multi-chip single-controller, and multi-controller
(``--multihost``): under a multi-process runtime the same jitted program
executes over the global mesh and each process only ever touches its
addressable shards.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from acg_tpu.ops.spmv import DiaMatrix
from acg_tpu.parallel.mesh import PARTS_AXIS, solve_mesh
from acg_tpu.solvers.jax_cg import JaxCGSolver


def sharded_poisson_dia(n: int, dim: int, mesh: Mesh, dtype=jnp.float32):
    """Poisson DIA planes assembled on device, sharded over ``mesh``.

    Returns ``(planes, offsets, N)``; each plane is an (N,) array laid
    out ``PartitionSpec(parts)`` over the mesh.  The computation is pure
    iota arithmetic, so XLA materialises each shard on its own device
    with no communication and no host data.
    """
    N = n ** dim
    sh = NamedSharding(mesh, P(PARTS_AXIS))

    @jax.jit
    def build():
        planes = []
        for a in range(dim):
            stride = n ** a
            coord = (jax.lax.iota(jnp.int32, N) // stride) % n
            planes.append(jnp.where(coord > 0, -1.0, 0.0).astype(dtype))
            planes.append(jnp.where(coord < n - 1, -1.0, 0.0).astype(dtype))
        planes.append(jnp.full((N,), float(2 * dim), dtype=dtype))
        return [jax.lax.with_sharding_constraint(p, sh) for p in planes]

    offsets = [s for a in range(dim) for s in (-(n ** a), n ** a)] + [0]
    order = np.argsort(offsets)
    planes = build()
    return ([planes[i] for i in order],
            tuple(int(offsets[i]) for i in order), N)


class ShardedDiaCGSolver(JaxCGSolver):
    """CG over a mesh-sharded square DIA matrix.

    A thin specialisation of :class:`JaxCGSolver`: the solve programs
    are unchanged -- input sharding alone turns them into SPMD programs
    (the role of ``acgsolvercuda_solvempi``'s explicit communicator
    plumbing, ``cgcuda.c:403-1143``, is played by GSPMD propagation).
    The SpMV is pinned to the roll formulation, whose shifts partition
    into neighbour collective-permutes (``kernels="xla-roll"``).
    """

    def __init__(self, A: DiaMatrix, mesh: Mesh | None = None,
                 pipelined: bool = False, precise_dots: bool = False,
                 vector_dtype=None):
        if A.ncols_padded != A.nrows:
            raise ValueError("sharded DIA solve needs a square matrix")
        super().__init__(A, pipelined=pipelined, precise_dots=precise_dots,
                         kernels="xla-roll", vector_dtype=vector_dtype)
        self.mesh = mesh if mesh is not None else solve_mesh()
        self.sharding = NamedSharding(self.mesh, P(PARTS_AXIS))

    def ones_b(self, dtype=None) -> jax.Array:
        """A sharded all-ones right-hand side (the CLI default b)."""
        dtype = dtype or self.vector_dtype or self.A.dtype
        return jax.jit(
            lambda: jnp.ones(self.A.nrows, dtype=dtype),
            out_shardings=self.sharding)()

    def manufactured(self, seed: int = 42):
        """``(xsol, b)`` on device, sharded: random unit-norm solution
        and ``b = A xsol`` via the same sharded SpMV (the role of the
        reference's manufactured-solution setup,
        ``cuda/acg-cuda.c:1969-2140``; the independent-oracle role of
        its host SpMV is covered at small sizes by the CPU-mesh tests,
        which check this b against scipy)."""
        from acg_tpu.ops.spmv import dia_mv_roll

        dtype = self.vector_dtype or self.A.dtype
        sdt = jnp.promote_types(dtype, jnp.float32)
        offsets = self.A.offsets
        nrows = self.A.nrows
        sharding = self.sharding

        # planes ride as ARGUMENTS: a jit may not close over arrays that
        # span other controllers' devices (multi-controller rule)
        @jax.jit
        def build(key, planes):
            xsol = jax.random.normal(key, (nrows,), dtype=sdt)
            xsol = (xsol / jnp.linalg.norm(xsol)).astype(dtype)
            xsol = jax.lax.with_sharding_constraint(xsol, sharding)
            b = dia_mv_roll(planes, offsets, xsol)
            return xsol, b

        return build(jax.random.key(seed), self.A.data)

    def error_norms(self, x, xsol):
        """``(err0, err)``: initial and final solution error 2-norms
        (device-side; only scalars reach the host)."""
        sdt = jnp.promote_types(x.dtype, jnp.float32)
        err = float(jnp.linalg.norm((x - xsol).astype(sdt)))
        err0 = float(jnp.linalg.norm(xsol.astype(sdt)))
        return err0, err


def build_sharded_poisson_solver(n: int, dim: int, nparts: int | None = None,
                                 dtype=jnp.float32, vector_dtype=None,
                                 pipelined: bool = False,
                                 precise_dots: bool = False,
                                 epsilon: float = 0.0):
    """Assemble a sharded Poisson problem and its solver in one call
    (the gen-direct CLI path under ``--nparts``/``--multihost``)."""
    mesh = solve_mesh(nparts)
    planes, offsets, N = sharded_poisson_dia(n, dim, mesh, dtype=dtype)
    if epsilon:
        d = offsets.index(0)
        sh = NamedSharding(mesh, P(PARTS_AXIS))
        planes = list(planes)
        planes[d] = jax.jit(
            lambda p: p + jnp.asarray(epsilon, p.dtype),
            out_shardings=sh)(planes[d])
    A = DiaMatrix(data=tuple(planes), offsets=offsets,
                  nrows=N, ncols_padded=N)
    return ShardedDiaCGSolver(A, mesh=mesh, pipelined=pipelined,
                              precise_dots=precise_dots,
                              vector_dtype=vector_dtype)
