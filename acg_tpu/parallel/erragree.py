"""Cross-controller error agreement -- the ``acgerrmpi`` analog.

The reference wraps hazardous stages in a collective error agreement so
every rank learns the worst error code and all exit together instead of
one rank dying alone while its peers wedge in the next collective
(``acg/error.c`` ``acgerrmpi``, used e.g. at ``cuda/acg-cuda.c:2410``).

TPU-native version: :func:`agree_status` allgathers an int32 status code
across controller processes at stage boundaries (host-local stages --
file I/O, partitioning -- are where one-sided failures happen; the solve
itself is one replicated SPMD program, so its failures are symmetric).
A watchdog guards the agreement itself: when a peer process died before
reaching the checkpoint, the allgather would block forever -- the
watchdog hard-exits this process with a distinct code after ``timeout``
seconds, so the pod tears down in seconds instead of hanging until the
scheduler's global timeout.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading

import numpy as np

# exit code for "a peer never reached the checkpoint" (distinct from any
# ErrorCode value; chosen in the 64..113 hole left by shell conventions)
PEER_LOST_EXIT = 97

# per-process sequence number making each checkpoint's KV keys unique;
# stays in lockstep across controllers because every agree_status call
# site is a symmetric stage boundary (the documented contract)
_seq = itertools.count()


def _coord_client():
    """The coordination-service KV client, or None -- plain gRPC to the
    coordinator, no device collective, so it works on backends whose
    multiprocess computations are unsupported (older CPU runtimes) and
    cannot be wedged by a poisoned accelerator."""
    try:
        from jax._src.distributed import global_state

        client = global_state.client
    except Exception:  # noqa: BLE001 -- internal API: fall back
        return None
    if (client is not None and hasattr(client, "key_value_set")
            and hasattr(client, "blocking_key_value_get")):
        return client
    return None


def _gather_codes(code: int, seq: int, timeout: float) -> list[int]:
    """All processes' status codes, via the coordination-service KV
    store when available; falls back to the allgather."""
    import jax

    n = jax.process_count()
    me = jax.process_index()
    client = _coord_client()
    if client is not None:
        base = f"acg_tpu/erragree/{seq}"
        client.key_value_set(f"{base}/{me}", str(int(code)))
        ms = max(int(timeout * 1000), 1)
        codes = [int(client.blocking_key_value_get(f"{base}/{q}", ms))
                 for q in range(n)]
        # bound coordinator memory on long-lived pods: generation seq-1
        # is finished on every controller (they could not be at seq
        # otherwise), so its keys are safe to drop -- deleting THIS
        # generation here would race peers still reading it
        if seq > 0 and hasattr(client, "key_value_delete"):
            try:
                client.key_value_delete(f"acg_tpu/erragree/{seq - 1}")
            except Exception:  # noqa: BLE001 -- cleanup, never fatal
                pass
        return codes
    from jax.experimental import multihost_utils

    return [int(c) for c in np.asarray(multihost_utils.process_allgather(
        np.int32(code), tiled=False)).ravel()]


# telemetry blob-gather generations, separate from the checkpoint
# sequence: telemetry gathers are OPTIONAL call sites (gated on the same
# CLI flags on every controller, so still symmetric) and must not
# perturb the erragree key lockstep
_blob_seq = itertools.count()


def allgather_blobs(blob: str, tag: str = "blob",
                    timeout: float = 120.0) -> list[str]:
    """Allgather one small UTF-8 string per process (the telemetry
    tier's cross-rank stats gather rides this).  Uses the erragree KV
    plumbing when the coordination service is up; falls back to a
    padded-bytes device allgather.  Every controller must call this at
    the same point (the ``agree_status`` contract); payloads should be
    kilobytes, not megabytes -- they transit the coordinator.
    """
    import jax

    n = jax.process_count()
    if n == 1:
        return [blob]
    me = jax.process_index()
    seq = next(_blob_seq)
    client = _coord_client()
    if client is not None:
        base = f"acg_tpu/{tag}/{seq}"
        client.key_value_set(f"{base}/{me}", blob)
        ms = max(int(timeout * 1000), 1)
        blobs = [client.blocking_key_value_get(f"{base}/{q}", ms)
                 for q in range(n)]
        if seq > 0 and hasattr(client, "key_value_delete"):
            try:
                client.key_value_delete(f"acg_tpu/{tag}/{seq - 1}")
            except Exception:  # noqa: BLE001 -- cleanup, never fatal
                pass
        return blobs
    # fallback: two fixed-shape allgathers (lengths, then padded bytes)
    from jax.experimental import multihost_utils

    data = np.frombuffer(blob.encode("utf-8"), dtype=np.uint8)
    lens = np.asarray(multihost_utils.process_allgather(
        np.int64(data.size), tiled=False)).ravel()
    width = int(lens.max(initial=1)) or 1
    buf = np.zeros(width, dtype=np.uint8)
    buf[: data.size] = data
    rows = np.asarray(multihost_utils.process_allgather(buf, tiled=False))
    return [bytes(rows[q, : int(lens[q])]).decode("utf-8")
            for q in range(n)]


def agree_status(code: int, what: str = "", timeout: float = 120.0) -> int:
    """Collective max of per-process status codes (0 = OK).

    Every controller must call this at the same stage boundary.  Returns
    the agreed (worst) code so callers can exit in unison.  If agreement
    does not complete within ``timeout`` seconds -- a peer crashed
    before its checkpoint -- the process prints a diagnosis and exits
    with :data:`PEER_LOST_EXIT`.

    ``timeout`` bounds the checkpoint-arrival *skew* between controllers,
    not the stage duration: the watchdog starts when THIS process reaches
    the checkpoint, so a healthy-but-slow peer (e.g. a replicated read of
    a large file from a slow filesystem arriving minutes after its peers)
    is indistinguishable from a dead one once the skew exceeds
    ``timeout``.  Size it for the worst-case stage imbalance, not the
    mean (``--err-timeout`` in the CLI).

    Single-process: returns ``code`` immediately (no collective).
    """
    import jax

    if jax.process_count() == 1:
        return int(code)

    # fault injector (acg_tpu.faults): a ``peer:dead``/``peer:stall``
    # spec makes the targeted controller die or stall HERE, before the
    # collective -- the exact failure shape the watchdog exists for,
    # reproducible on the CPU pod without killing real processes
    from acg_tpu.faults import maybe_fail_peer
    maybe_fail_peer(what)

    seq = next(_seq)
    done = threading.Event()

    def _abort():
        if done.is_set():
            # agreement completed in the race window between the
            # allgather returning and the timer being cancelled
            return
        sys.stderr.write(
            f"acg-tpu: error agreement{' (' + what + ')' if what else ''} "
            f"timed out after {timeout:.0f}s -- a peer controller died "
            f"before its checkpoint; aborting this process\n")
        sys.stderr.flush()
        os._exit(PEER_LOST_EXIT)

    watchdog = threading.Timer(timeout, _abort)
    watchdog.daemon = True
    watchdog.start()
    try:
        codes = _gather_codes(code, seq, timeout)
        done.set()
    except Exception as e:  # noqa: BLE001 -- a failed collective here
        # means a peer died mid-connection; same teardown as a timeout
        watchdog.cancel()
        sys.stderr.write(
            f"acg-tpu: error agreement{' (' + what + ')' if what else ''} "
            f"failed ({type(e).__name__}) -- a peer controller died; "
            f"aborting this process\n")
        sys.stderr.flush()
        os._exit(PEER_LOST_EXIT)
    finally:
        watchdog.cancel()
    return int(np.max(codes))
