"""Cross-controller error agreement -- the ``acgerrmpi`` analog.

The reference wraps hazardous stages in a collective error agreement so
every rank learns the worst error code and all exit together instead of
one rank dying alone while its peers wedge in the next collective
(``acg/error.c`` ``acgerrmpi``, used e.g. at ``cuda/acg-cuda.c:2410``).

TPU-native version: :func:`agree_status` allgathers an int32 status code
across controller processes at stage boundaries (host-local stages --
file I/O, partitioning -- are where one-sided failures happen; the solve
itself is one replicated SPMD program, so its failures are symmetric).
A watchdog guards the agreement itself: when a peer process died before
reaching the checkpoint, the allgather would block forever -- the
watchdog hard-exits this process with a distinct code after ``timeout``
seconds, so the pod tears down in seconds instead of hanging until the
scheduler's global timeout.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading

import numpy as np

# exit code for "a peer never reached the checkpoint" (the process-wide
# contract lives in errors.ExitCode; --buildinfo renders the table)
from acg_tpu.errors import ExitCode as _ExitCode

PEER_LOST_EXIT = int(_ExitCode.PEER_LOST)

# per-process sequence number making each checkpoint's KV keys unique;
# stays in lockstep across controllers because every agree_status call
# site is a symmetric stage boundary (the documented contract)
_seq = itertools.count()


def _coord_client():
    """The coordination-service KV client, or None -- plain gRPC to the
    coordinator, no device collective, so it works on backends whose
    multiprocess computations are unsupported (older CPU runtimes) and
    cannot be wedged by a poisoned accelerator."""
    try:
        from jax._src.distributed import global_state

        client = global_state.client
    except Exception:  # noqa: BLE001 -- internal API: fall back
        return None
    if (client is not None and hasattr(client, "key_value_set")
            and hasattr(client, "blocking_key_value_get")):
        return client
    return None


def _gather_codes(code: int, seq: int, timeout: float) -> list[int]:
    """All processes' status codes, via the coordination-service KV
    store when available; falls back to the allgather."""
    import jax

    n = jax.process_count()
    me = jax.process_index()
    client = _coord_client()
    if client is not None:
        base = f"acg_tpu/erragree/{seq}"
        client.key_value_set(f"{base}/{me}", str(int(code)))
        ms = max(int(timeout * 1000), 1)
        codes = [int(client.blocking_key_value_get(f"{base}/{q}", ms))
                 for q in range(n)]
        # bound coordinator memory on long-lived pods: generation seq-1
        # is finished on every controller (they could not be at seq
        # otherwise), so its keys are safe to drop -- deleting THIS
        # generation here would race peers still reading it
        if seq > 0 and hasattr(client, "key_value_delete"):
            try:
                client.key_value_delete(f"acg_tpu/erragree/{seq - 1}")
            except Exception:  # noqa: BLE001 -- cleanup, never fatal
                pass
        return codes
    from jax.experimental import multihost_utils

    return [int(c) for c in np.asarray(multihost_utils.process_allgather(
        np.int32(code), tiled=False)).ravel()]


# telemetry blob-gather generations, separate from the checkpoint
# sequence: telemetry gathers are OPTIONAL call sites (gated on the same
# CLI flags on every controller, so still symmetric) and must not
# perturb the erragree key lockstep
_blob_seq = itertools.count()


def barrier(tag: str = "barrier", timeout: float = 120.0) -> float:
    """Rendezvous all controller processes and return ``time.time()``
    taken IMMEDIATELY after every rank exited -- the timeline tier's
    clock-alignment stamp (acg_tpu.tracing.align_payloads): the true
    exit event is simultaneous up to gather jitter, so any difference
    between ranks' stamps is clock skew.  Rides the blob-gather
    plumbing (same symmetric-call-site contract)."""
    import time

    allgather_blobs("1", tag=tag, timeout=timeout)
    return time.time()


def allgather_blobs(blob: str, tag: str = "blob",
                    timeout: float = 120.0) -> list[str]:
    """Allgather one small UTF-8 string per process (the telemetry
    tier's cross-rank stats gather rides this).  Uses the erragree KV
    plumbing when the coordination service is up; falls back to a
    padded-bytes device allgather.  Every controller must call this at
    the same point (the ``agree_status`` contract); payloads should be
    kilobytes, not megabytes -- they transit the coordinator.
    """
    import jax

    n = jax.process_count()
    if n == 1:
        return [blob]
    me = jax.process_index()
    seq = next(_blob_seq)
    client = _coord_client()
    if client is not None:
        base = f"acg_tpu/{tag}/{seq}"
        client.key_value_set(f"{base}/{me}", blob)
        ms = max(int(timeout * 1000), 1)
        blobs = [client.blocking_key_value_get(f"{base}/{q}", ms)
                 for q in range(n)]
        if seq > 0 and hasattr(client, "key_value_delete"):
            try:
                client.key_value_delete(f"acg_tpu/{tag}/{seq - 1}")
            except Exception:  # noqa: BLE001 -- cleanup, never fatal
                pass
        return blobs
    # fallback: two fixed-shape allgathers (lengths, then padded bytes)
    from jax.experimental import multihost_utils

    data = np.frombuffer(blob.encode("utf-8"), dtype=np.uint8)
    lens = np.asarray(multihost_utils.process_allgather(
        np.int64(data.size), tiled=False)).ravel()
    width = int(lens.max(initial=1)) or 1
    buf = np.zeros(width, dtype=np.uint8)
    buf[: data.size] = data
    rows = np.asarray(multihost_utils.process_allgather(buf, tiled=False))
    return [bytes(rows[q, : int(lens[q])]).decode("utf-8")
            for q in range(n)]


class DeadlineHeartbeat:
    """Dead-peer detection DURING the solve collective (the
    survivability tier, acg_tpu.checkpoint).

    :func:`agree_status`'s watchdog only guards the agreement
    checkpoints BETWEEN stages -- a controller that dies mid-solve
    leaves its peers wedged inside an XLA collective that no Python
    watchdog wraps, until the scheduler's global timeout.  The
    heartbeat closes that hole: every controller bumps a
    coordination-service key every ``period`` seconds from a daemon
    thread (plain gRPC to the coordinator -- runs happily while the
    main thread is blocked in a device collective), and watches its
    peers' keys; a peer whose beat has not advanced for ``deadline``
    seconds is declared dead and THIS process tears down with
    :data:`PEER_LOST_EXIT` -- at which point the supervisor relaunches
    the pod with ``--resume`` and the solve continues from the last
    agreed snapshot (rollback), or operators abort.  That relaunch IS
    the rollback-vs-abort decision for a process killed outright: the
    survivors cannot vote with a dead peer, so the policy lives in the
    snapshot (a ``--ckpt``-armed solve rolls back; an unarmed one can
    only abort).

    Single-process (or no coordination service): :meth:`start` is a
    no-op and :meth:`stop` returns immediately, so the call sites need
    no gating.  ``on_lost`` overrides the hard exit (tests)."""

    def __init__(self, period: float = 5.0, deadline: float = 30.0,
                 what: str = "solve", on_lost=None, client=None,
                 nprocs: int | None = None, me: int | None = None):
        if period <= 0 or deadline <= period:
            raise ValueError("heartbeat needs 0 < period < deadline "
                             f"(got period={period}, deadline={deadline})")
        self.period = float(period)
        self.deadline = float(deadline)
        self.what = str(what)
        self.on_lost = on_lost
        self._client = client
        self._nprocs = nprocs
        self._me = me
        self._stop = threading.Event()
        self._thread = None
        self._gen = next(_blob_seq)
        # (last seen value, monotonic time it changed) per peer --
        # written by the beat thread, read by peer_ages() (the status
        # document's peers: block); dict assignment is atomic under
        # the GIL, so no lock
        self._seen: dict = {}

    def _lost(self, peer: int, age: float) -> None:
        if self.on_lost is not None:
            self.on_lost(peer, age)
            return
        sys.stderr.write(
            f"acg-tpu: heartbeat ({self.what}): controller {peer} "
            f"silent for {age:.0f}s (deadline {self.deadline:.0f}s) -- "
            f"peer died mid-solve; aborting this process (relaunch "
            f"with --resume to roll back to the last snapshot)\n")
        sys.stderr.flush()
        os._exit(PEER_LOST_EXIT)

    def _run(self, client, n: int, me: int) -> None:
        import time as _time

        base = f"acg_tpu/heartbeat/{self._gen}"
        beat = 0
        seen = self._seen
        while not self._stop.wait(self.period):
            beat += 1
            try:
                client.key_value_set(f"{base}/{me}/{beat}", "1")
            except Exception:  # noqa: BLE001 -- coordinator gone: the
                # erragree watchdogs own that teardown, not us
                return
            if beat > 1:
                try:
                    # retire the previous beat so a multi-hour solve
                    # does not grow the coordinator's store (and the
                    # peers' directory listings) without bound
                    client.key_value_delete(f"{base}/{me}/{beat - 1}")
                except Exception:  # noqa: BLE001 -- delete unsupported
                    pass               # on this client: keys just pile up
            now = _time.monotonic()
            for q in range(n):
                if q == me:
                    continue
                try:
                    # the peer's progress counter is the HIGHEST beat
                    # index under its directory (not the row count:
                    # beaters retire old keys when the client allows)
                    rows = client.key_value_dir_get(f"{base}/{q}")
                    val = str(max(
                        (int(str(k).rsplit("/", 1)[-1])
                         for k, _ in rows), default=0))
                except Exception:  # noqa: BLE001 -- not written yet
                    val = ""
                prev = seen.get(q)
                if prev is None or prev[0] != val:
                    seen[q] = (val, now)
                    continue
                age = now - prev[1]
                if age > self.deadline:
                    self._lost(q, age)
                    return

    def peer_ages(self) -> dict:
        """Seconds since each watched peer's beat last ADVANCED
        (controller index -> age; empty before the first watch pass or
        single-process) -- the live-status ``peers:`` block's payload.
        An age approaching ``deadline`` is a peer about to be declared
        dead."""
        import time as _time

        now = _time.monotonic()
        return {int(q): max(0.0, now - t)
                for q, (_v, t) in list(self._seen.items())}

    def start(self) -> "DeadlineHeartbeat":
        import jax

        n = self._nprocs if self._nprocs is not None else jax.process_count()
        me = self._me if self._me is not None else jax.process_index()
        client = self._client if self._client is not None else _coord_client()
        if n == 1 or client is None:
            return self
        if not hasattr(client, "key_value_dir_get"):
            return self
        self._thread = threading.Thread(
            target=self._run, args=(client, n, me),
            name="acg-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.period)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def agree_status(code: int, what: str = "", timeout: float = 120.0) -> int:
    """Collective max of per-process status codes (0 = OK).

    Every controller must call this at the same stage boundary.  Returns
    the agreed (worst) code so callers can exit in unison.  If agreement
    does not complete within ``timeout`` seconds -- a peer crashed
    before its checkpoint -- the process prints a diagnosis and exits
    with :data:`PEER_LOST_EXIT`.

    ``timeout`` bounds the checkpoint-arrival *skew* between controllers,
    not the stage duration: the watchdog starts when THIS process reaches
    the checkpoint, so a healthy-but-slow peer (e.g. a replicated read of
    a large file from a slow filesystem arriving minutes after its peers)
    is indistinguishable from a dead one once the skew exceeds
    ``timeout``.  Size it for the worst-case stage imbalance, not the
    mean (``--err-timeout`` in the CLI).

    Single-process: returns ``code`` immediately (no collective).
    """
    import jax

    if jax.process_count() == 1:
        return int(code)

    # fault injector (acg_tpu.faults): a ``peer:dead``/``peer:stall``
    # spec makes the targeted controller die or stall HERE, before the
    # collective -- the exact failure shape the watchdog exists for,
    # reproducible on the CPU pod without killing real processes
    from acg_tpu.faults import maybe_fail_peer
    maybe_fail_peer(what)

    seq = next(_seq)
    done = threading.Event()

    def _abort():
        if done.is_set():
            # agreement completed in the race window between the
            # allgather returning and the timer being cancelled
            return
        sys.stderr.write(
            f"acg-tpu: error agreement{' (' + what + ')' if what else ''} "
            f"timed out after {timeout:.0f}s -- a peer controller died "
            f"before its checkpoint; aborting this process\n")
        sys.stderr.flush()
        os._exit(PEER_LOST_EXIT)

    watchdog = threading.Timer(timeout, _abort)
    watchdog.daemon = True
    watchdog.start()
    try:
        codes = _gather_codes(code, seq, timeout)
        done.set()
    except Exception as e:  # noqa: BLE001 -- a failed collective here
        # means a peer died mid-connection; same teardown as a timeout
        watchdog.cancel()
        sys.stderr.write(
            f"acg-tpu: error agreement{' (' + what + ')' if what else ''} "
            f"failed ({type(e).__name__}) -- a peer controller died; "
            f"aborting this process\n")
        sys.stderr.flush()
        os._exit(PEER_LOST_EXIT)
    finally:
        watchdog.cancel()
    return int(np.max(codes))
