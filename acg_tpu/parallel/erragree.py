"""Cross-controller error agreement -- the ``acgerrmpi`` analog.

The reference wraps hazardous stages in a collective error agreement so
every rank learns the worst error code and all exit together instead of
one rank dying alone while its peers wedge in the next collective
(``acg/error.c`` ``acgerrmpi``, used e.g. at ``cuda/acg-cuda.c:2410``).

TPU-native version: :func:`agree_status` allgathers an int32 status code
across controller processes at stage boundaries (host-local stages --
file I/O, partitioning -- are where one-sided failures happen; the solve
itself is one replicated SPMD program, so its failures are symmetric).
A watchdog guards the agreement itself: when a peer process died before
reaching the checkpoint, the allgather would block forever -- the
watchdog hard-exits this process with a distinct code after ``timeout``
seconds, so the pod tears down in seconds instead of hanging until the
scheduler's global timeout.
"""

from __future__ import annotations

import os
import sys
import threading

import numpy as np

# exit code for "a peer never reached the checkpoint" (distinct from any
# ErrorCode value; chosen in the 64..113 hole left by shell conventions)
PEER_LOST_EXIT = 97


def agree_status(code: int, what: str = "", timeout: float = 120.0) -> int:
    """Collective max of per-process status codes (0 = OK).

    Every controller must call this at the same stage boundary.  Returns
    the agreed (worst) code so callers can exit in unison.  If agreement
    does not complete within ``timeout`` seconds -- a peer crashed
    before its checkpoint -- the process prints a diagnosis and exits
    with :data:`PEER_LOST_EXIT`.

    ``timeout`` bounds the checkpoint-arrival *skew* between controllers,
    not the stage duration: the watchdog starts when THIS process reaches
    the checkpoint, so a healthy-but-slow peer (e.g. a replicated read of
    a large file from a slow filesystem arriving minutes after its peers)
    is indistinguishable from a dead one once the skew exceeds
    ``timeout``.  Size it for the worst-case stage imbalance, not the
    mean (``--err-timeout`` in the CLI).

    Single-process: returns ``code`` immediately (no collective).
    """
    import jax

    if jax.process_count() == 1:
        return int(code)

    from jax.experimental import multihost_utils

    done = threading.Event()

    def _abort():
        if done.is_set():
            # agreement completed in the race window between the
            # allgather returning and the timer being cancelled
            return
        sys.stderr.write(
            f"acg-tpu: error agreement{' (' + what + ')' if what else ''} "
            f"timed out after {timeout:.0f}s -- a peer controller died "
            f"before its checkpoint; aborting this process\n")
        sys.stderr.flush()
        os._exit(PEER_LOST_EXIT)

    watchdog = threading.Timer(timeout, _abort)
    watchdog.daemon = True
    watchdog.start()
    try:
        codes = multihost_utils.process_allgather(
            np.int32(code), tiled=False)
        done.set()
    except Exception as e:  # noqa: BLE001 -- a failed collective here
        # means a peer died mid-connection; same teardown as a timeout
        watchdog.cancel()
        sys.stderr.write(
            f"acg-tpu: error agreement{' (' + what + ')' if what else ''} "
            f"failed ({type(e).__name__}) -- a peer controller died; "
            f"aborting this process\n")
        sys.stderr.flush()
        os._exit(PEER_LOST_EXIT)
    finally:
        watchdog.cancel()
    return int(np.max(codes))
