"""Device-initiated halo exchange: Pallas one-sided remote DMA over ICI.

The TPU-native analog of the reference's NVSHMEM transport (SURVEY.md
components #11/#14): where the reference's monolithic kernel issues
``nvshmemx_double_put_signal_nbi_block`` per neighbour and spins on
``signal_wait_until`` flags (``cg-kernels-cuda.cu:713-776``,
``halo.cu:181-242``), this kernel issues ``pltpu.make_async_remote_copy``
per neighbour (a put that signals the receiver's DMA semaphore) and waits
on the matching semaphores.

Structure: nparts-1 rotation rounds; in round s every shard puts its
window for shard ``me+s`` and receives from shard ``me-s`` -- the
systolic all-to-all schedule that keeps traffic on ICI neighbours first.
Pallas interpret mode (CPU meshes, tests) additionally *requires* this
uniformity: it emulates remote DMA with collectives that pair DMA ops
across devices in issue order, so any per-shard divergence in the op
sequence -- different ordering, or count-gated skips that are not
globally uniform per round -- deadlocks or mis-routes.

Synchronisation details:
  * One scalar send and one scalar recv DMA semaphore are shared by all
    rounds.  Every put moves exactly ``maxcnt`` elements (windows are
    padded to the mesh-wide maximum, like the reference's NVSHMEM
    symmetric buffers, ``halo.c:883-887``), so the shared-semaphore
    waits are exact regardless of completion order.
  * On real TPUs, puts and waits are gated by the per-neighbour counts
    (only real neighbours communicate -- the reference's per-neighbour
    ``sendcounts``, ``halo.h:72-186``).  Interpret mode must issue a
    globally uniform op sequence, so there the exchange is dense; the
    gating arithmetic itself is still covered on CPU by a
    ring-structured test whose gate pattern is uniform per round.
  * On real TPUs a neighbourhood barrier at kernel entry reproduces the
    reference's ``readytoreceive`` handshake (``halo.c:957-967``): a TPU
    core runs its program in order, so a neighbour entering this kernel
    proves it has consumed the previous exchange's buffers.  Interpret
    mode has no barrier primitive and skips it (its DMA emulation
    rendezvouses on fresh buffers, so the hazard does not exist there).
  * Receive-plane rows of non-neighbours are never written; the unpack
    masks padding ghost slots (``ghost_valid``) so those uninitialised
    rows are never observed.

Selected by ``--comm dma`` (the reference's ``--comm nvshmem``); the
default ``--comm xla`` transport is the `lax.all_to_all` in
:mod:`acg_tpu.parallel.halo`.  Pack/unpack stay XLA gathers outside the
kernel, exactly as the reference keeps its pack kernels separate from the
transport (``halo.cu:41-107``).

Validation status: the gating, routing, and barrier-count logic are all
exercised in CI (interpret mode, uniform-gate rings plus randomized
star/line/clustered topologies vs the xla transport); the compiled
multi-chip path has NOT yet run on real ICI -- this build's environment
exposes one chip -- so first contact on a pod slice should start with
``--comm xla`` agreement checks at small sizes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from acg_tpu.parallel.mesh import PARTS_AXIS


_dma_status: tuple | None = None


def dma_transport_status(refresh: bool = False) -> tuple:
    """Cached ``(available, why)`` capability verdict for the one-sided
    transport in THIS process topology -- the conftest two-process-probe
    pattern, library-side.

    Single-controller: available (the compiled put-with-signal path is
    proven on silicon by ``scripts/dma_probe.py``; interpret mode is
    CI-covered).  Multi-controller on TPU: unavailable -- the compiled
    multi-chip path has never run on real ICI, and a wrong guess
    deadlocks a pod, so the verdict is a self-describing downgrade, not
    a probe.  Multi-controller off-TPU: the interpret emulation pairs
    DMA ops with collectives, so the probe ATTEMPTS one tiny
    cross-process psum over a mesh with ONE DEVICE PER PROCESS (every
    controller reaches solver setup together, so the collective is
    matched) and then AGREES the verdict across controllers over the
    erragree blob allgather -- a locally-divergent verdict would arm
    mismatched transports (DMA puts on one controller, all_to_all on
    another) and deadlock the very first halo exchange, the failure
    mode the old hard refusal protected against.  ``DistCGSolver``
    downgrades ``comm='dma'`` to the xla transport with a recorded
    event when this says no."""
    global _dma_status
    if _dma_status is not None and not refresh:
        return _dma_status
    if jax.process_count() == 1:
        _dma_status = (True, "")
        return _dma_status
    if jax.devices()[0].platform == "tpu":
        _dma_status = (
            False,
            "the compiled multi-chip put-with-signal path has never "
            "run on real ICI (scripts/dma_probe.py pins the "
            "single-chip lowering only)")
        return _dma_status
    try:
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from acg_tpu._platform import shard_map as _sm
        from acg_tpu.parallel.multihost import put_global
        # one device per PROCESS: jax.devices()[:n] can be all local,
        # which would probe nothing cross-process
        by_proc: dict = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        devs = np.asarray([by_proc[p] for p in sorted(by_proc)])
        mesh = Mesh(devs, ("probe",))
        f = jax.jit(_sm(lambda a: lax.psum(a, "probe"), mesh=mesh,
                        in_specs=P("probe"), out_specs=P()))
        a = put_global(np.ones((devs.size,), np.float32),
                       sharding=NamedSharding(mesh, P("probe")))
        np.asarray(f(a))
        mine = (True, "")
    except Exception as e:  # noqa: BLE001 -- the probe must conclude
        mine = (False, "cross-process collectives unavailable on this "
                f"backend ({type(e).__name__})")
    # ONE agreed verdict: any controller failing downgrades them all
    # (the erragree every-controller-calls-here contract holds -- all
    # controllers construct the solver at the same program point)
    try:
        from acg_tpu.parallel.erragree import allgather_blobs
        got = allgather_blobs("ok" if mine[0] else "no",
                              tag="dma-probe")
        if all(g == "ok" for g in got):
            _dma_status = (True, "")
        else:
            _dma_status = (False, mine[1] if not mine[0] else
                           "a peer controller's transport probe failed")
    except Exception as e:  # noqa: BLE001 -- no agreement, no arming
        _dma_status = (False, "transport-probe verdict agreement "
                       f"failed ({type(e).__name__})")
    return _dma_status


def _compiler_params(**kwargs):
    """Mosaic compiler params across jax versions: the class was renamed
    TPUCompilerParams -> CompilerParams and older ones lack
    ``has_side_effects`` (safe to drop -- the exchange output is consumed
    by the unpack gather, so the kernel is never dead code)."""
    import dataclasses

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    fields = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kwargs.items() if k in fields})


def _exchange_kernel(axis, use_barrier, gate_by_counts, scnt_ref, rcnt_ref,
                     sendbuf_ref, recvbuf_ref, send_sem, recv_sem):
    """Per-shard kernel: neighbourhood barrier, start every gated put
    (nbi-style, all in flight at once), then wait for sends and
    receives."""
    me = lax.axis_index(axis)
    # static mesh size; lax.axis_size is missing on older runtimes, where
    # psum of a Python scalar is the (statically folded) idiom
    nparts = (lax.axis_size(axis) if hasattr(lax, "axis_size")
              else lax.psum(1, axis))

    def want_send(q):
        if gate_by_counts:
            return scnt_ref[q] > 0
        return jnp.asarray(True)

    def want_recv(q):
        if gate_by_counts:
            return rcnt_ref[q] > 0
        return jnp.asarray(True)

    if use_barrier:
        # readytoreceive handshake with the neighbourhood (halo.c:957-967)
        barrier = pltpu.get_barrier_semaphore()
        nneighbors = jnp.int32(0)
        for s in range(1, nparts):
            q = (me + s) % nparts
            is_neighbor = want_send(q) | want_recv(q)

            @pl.when(is_neighbor)
            def _(q=q):
                pltpu.semaphore_signal(
                    barrier, inc=1, device_id=q,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
            nneighbors = nneighbors + jnp.where(is_neighbor, 1, 0)
        pltpu.semaphore_wait(barrier, nneighbors)

    def put_descriptor(peer, src_row, dst_row):
        # put-with-signal (cg-kernels-cuda.cu:734-746): the window lands
        # in the peer's recvbuf row and signals the peer's recv semaphore
        return pltpu.make_async_remote_copy(
            src_ref=sendbuf_ref.at[src_row],
            dst_ref=recvbuf_ref.at[dst_row],
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=peer,
            device_id_type=pltpu.DeviceIdType.LOGICAL)

    # start all puts before waiting on any (the reference's _nbi puts,
    # cg-kernels-cuda.cu:734-746): distinct source and destination rows,
    # so every transfer is independent and overlaps on the wire
    for s in range(1, nparts):
        dst = (me + s) % nparts

        @pl.when(want_send(dst))
        def _(dst=dst):
            put_descriptor(dst, dst, me).start()

    for s in range(1, nparts):
        dst = (me + s) % nparts
        src = (me - s + nparts) % nparts

        @pl.when(want_send(dst))
        def _(dst=dst):
            put_descriptor(dst, dst, me).wait_send()

        @pl.when(want_recv(src))
        def _(src=src):
            # signal_wait_until analog: src's put into my row `src`
            put_descriptor(src, src, src).wait_recv()


@functools.partial(jax.jit,
                   static_argnames=("axis", "interpret", "gate_by_counts"))
def _exchange(sendbuf, send_counts, recv_counts, axis: str, interpret: bool,
              gate_by_counts: bool | None = None):
    nparts, maxcnt = sendbuf.shape
    if nparts == 1:
        # no neighbours, no puts: the receive plane is never written and
        # every ghost gather is masked by ghost_valid.  Short-circuit
        # instead of compiling the degenerate kernel -- measured on real
        # hardware (2026-07-30): Mosaic SIGABRTs compiling the empty-put
        # barrier kernel, while a 1-device kernel with an actual
        # self-put + barrier compiles and runs correctly
        # (scripts/dma_probe.py holds the repro of both).
        return jnp.zeros_like(sendbuf)
    if gate_by_counts is None:
        gate_by_counts = not interpret
    kernel = functools.partial(_exchange_kernel, axis, not interpret,
                               gate_by_counts)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((nparts, maxcnt), sendbuf.dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # send_counts
            pl.BlockSpec(memory_space=pltpu.SMEM),   # recv_counts
            pl.BlockSpec(memory_space=pl.ANY),       # sendbuf
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),             # send (shared)
            pltpu.SemaphoreType.DMA(()),             # recv (shared)
        ],
        compiler_params=_compiler_params(has_side_effects=True,
                                         collective_id=0),
        interpret=interpret,
    )(send_counts, recv_counts, sendbuf)


def dma_exchange(sendbuf: jax.Array, send_counts: jax.Array,
                 recv_counts: jax.Array, axis: str = PARTS_AXIS,
                 interpret: bool | None = None,
                 gate_by_counts: bool | None = None) -> jax.Array:
    """The raw systolic put-with-signal exchange without pack/unpack --
    the communication observatory's probe entry (acg_tpu.commbench:
    dense window sweeps and the per-edge put/wait timing rows, whose
    distance gates are globally uniform per rotation round and so are
    safe under the interpret emulation's op pairing).  Same contract as
    the :func:`_exchange` kernel the solve-path transport rides, same
    interpret default as :func:`halo_exchange_dma`."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _exchange(sendbuf, send_counts, recv_counts, axis, interpret,
                     gate_by_counts)


def halo_exchange_dma(x_loc: jax.Array, send_idx: jax.Array,
                      ghost_src: jax.Array, ghost_valid: jax.Array,
                      send_counts: jax.Array, recv_counts: jax.Array,
                      axis: str = PARTS_AXIS,
                      interpret: bool | None = None) -> jax.Array:
    """Exchange ghost values by one-sided remote DMA; call inside
    `shard_map` over ``axis``.

    Same contract as :func:`acg_tpu.parallel.halo.halo_exchange` plus the
    per-neighbour counts (``send_counts[q]`` = entries this shard sends to
    shard q), which gate the puts so only real neighbours communicate,
    and ``ghost_valid``, which masks padding ghost slots whose gathers
    would otherwise read receive-plane rows no neighbour ever wrote
    (uninitialised device memory on real TPUs).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    sendbuf = x_loc[send_idx]                    # pack (halo.cu:41-54)
    recvbuf = _exchange(sendbuf, send_counts, recv_counts, axis,
                        interpret)
    ghost = recvbuf.reshape(-1)[ghost_src]       # unpack (halo.cu:94-107)
    return jnp.where(ghost_valid, ghost, 0)
