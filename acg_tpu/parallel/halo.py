"""Device-side halo exchange over the mesh.

Rebuilds the reference's halo engine (``acg/halo.c``, ``acg/halo.cu``,
SURVEY.md components #13-14) in XLA-collective form: the host-side plan
(per-neighbour index lists, :class:`acg_tpu.graph.HaloPlan`) is compiled
into static padded gather/scatter index arrays, and the transport is a
single `lax.all_to_all` over the ``parts`` mesh axis inside `shard_map`.

Mapping of the reference's mechanisms:
  * pack kernel (``halo.cu:41-54``: ``sendbuf[i] = src[sendbufidx[i]]``)
    -> one gather ``x[send_idx]`` producing the (nparts, maxcnt) send plane;
  * MPI persistent-request / NCCL grouped send-recv transport
    (``halo.c:1077-1090,1272-1330``) -> `lax.all_to_all` over ICI;
  * unpack kernel (``halo.cu:94-107``) -> one gather from the received
    plane into the ghost slots (``ghost_src``);
  * NVSHMEM max-size symmetric buffers (``halo.c:883-887``) -> the same
    pad-to-max trick, required here by XLA's static shapes: every
    (src, dst) window is padded to the mesh-wide maximum count.

A Pallas remote-DMA transport (the device-initiated put-with-signal analog)
lives in ``acg_tpu.parallel.halo_dma`` and is selected by ``--comm dma``;
the hand-written compute kernels live in ``acg_tpu.ops.pallas_kernels``.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import numpy as np
from jax import lax

from acg_tpu.graph import Subdomain
from acg_tpu.parallel.mesh import PARTS_AXIS


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=["send_idx", "ghost_src", "ghost_valid"],
                   meta_fields=["maxcnt", "nmax_ghost", "nparts"])
@dataclasses.dataclass
class DeviceHaloPlan:
    """Static padded halo plan, stacked over parts (leading axis sharded).

    ``send_idx[p, q, :]`` gathers from part p's owned vector the window it
    sends to part q (padded with index 0; padding values are never read on
    the receive side).  ``ghost_src[p, g]`` indexes the flattened received
    plane (nparts * maxcnt) to fill ghost slot g of part p.
    ``ghost_valid[p, g]`` is 0 for padding slots beyond part p's real
    ghost count: their ghost_src of 0 would read a receive-plane row that
    the DMA transport may never have written (uninitialised device
    memory), so the unpack masks them to zero.
    """

    send_idx: jax.Array     # (nparts, nparts, maxcnt) int32
    ghost_src: jax.Array    # (nparts, nmax_ghost) int32
    ghost_valid: jax.Array  # (nparts, nmax_ghost) bool
    maxcnt: int
    nmax_ghost: int
    nparts: int

    @property
    def has_ghosts(self) -> bool:
        return self.nmax_ghost > 0 and self.maxcnt > 0


def build_device_halo(subs: list[Subdomain], maxcnt: int | None = None,
                      nmax_ghost: int | None = None) -> DeviceHaloPlan:
    """Compile host halo plans into padded device index arrays.

    ``maxcnt``/``nmax_ghost`` override the locally-derived maxima in the
    local-read flow, where this controller only holds its own parts'
    plans (parts with ``halo is None`` are skipped; their rows stay as
    untouched calloc pages and their device shards are filled by the
    owning controller)."""
    nparts = len(subs)
    if maxcnt is None:
        maxcnt = max((int(c) for s in subs if s.halo is not None
                      for c in s.halo.send_counts), default=0)
    if nmax_ghost is None:
        nmax_ghost = max((s.nghost for s in subs), default=0)
    send_idx = np.zeros((nparts, nparts, max(maxcnt, 1)), dtype=np.int32)
    ghost_src = np.zeros((nparts, max(nmax_ghost, 1)), dtype=np.int32)
    ghost_valid = np.zeros((nparts, max(nmax_ghost, 1)), dtype=bool)
    for p, s in enumerate(subs):
        if s.halo is None:
            continue
        ghost_valid[p, : s.nghost] = True
        h = s.halo
        for j, q in enumerate(h.send_parts):
            w = h.send_idx[h.send_ptr[j]:h.send_ptr[j + 1]]
            send_idx[p, int(q), : w.size] = w
        # ghost slot g of part p comes from owner q's send window to p, at
        # the slot's rank within its (contiguous, global-id-sorted) window
        for j, q in enumerate(h.recv_parts):
            lo, hi = int(h.recv_ptr[j]), int(h.recv_ptr[j + 1])
            ghost_src[p, lo:hi] = int(q) * max(maxcnt, 1) + np.arange(hi - lo)
    # arrays stay HOST numpy: device placement goes through put_global's
    # per-shard slicing (multi-controller processes must not materialise
    # full device copies of other processes' shards)
    return DeviceHaloPlan(send_idx=send_idx, ghost_src=ghost_src,
                          ghost_valid=ghost_valid,
                          maxcnt=maxcnt, nmax_ghost=nmax_ghost, nparts=nparts)


def halo_exchange(x_loc: jax.Array, send_idx: jax.Array,
                  ghost_src: jax.Array, axis: str = PARTS_AXIS) -> jax.Array:
    """Exchange ghost values; call inside `shard_map` over ``axis``.

    Per shard: ``x_loc`` (nmax_owned,), ``send_idx`` (nparts, maxcnt),
    ``ghost_src`` (nmax_ghost,).  Returns the ghost vector (nmax_ghost,).
    """
    with jax.named_scope("halo_exchange_xla"):
        sendbuf = x_loc[send_idx]                   # pack: (nparts, maxcnt)
        recvbuf = lax.all_to_all(sendbuf, axis, split_axis=0, concat_axis=0,
                                 tiled=True)        # transport over ICI
        return recvbuf.reshape(-1)[ghost_src]       # unpack into ghost slots
