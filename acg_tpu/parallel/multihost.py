"""Multi-controller (multi-host) runtime glue.

The reference boots one MPI rank per GPU and derives every communicator
from ``MPI_COMM_WORLD`` (``cuda/acg-cuda.c:891-1203``; NCCL unique-id
broadcast ``:1110-1121``; NVSHMEM bootstrap ``comm-nvshmem.cu:84-100``).
The TPU-native analog is JAX's multi-controller runtime: one Python
process per host, :func:`jax.distributed.initialize` playing the role of
``MPI_Init``, and the *global* device list playing the role of the
communicator.  The jitted SPMD solve program is unchanged -- each process
traces the identical program over the global mesh and XLA runs the
collectives over ICI/DCN; only array ingress/egress differ, because each
process can address only its local shards.

Entry points:

* :func:`initialize` -- idempotent ``jax.distributed.initialize``; on TPU
  pods all arguments are auto-detected from the environment, elsewhere
  (and in the CPU smoke test) coordinator/process counts are explicit.
* :func:`put_global` / :func:`get_global` -- host-array placement onto a
  possibly multi-process sharding and back.  Single-process these reduce
  to ``device_put`` / ``device_get``.
* :func:`is_primary` -- "rank 0" predicate for stdout/stderr output (the
  reference prints stats and the solution from rank 0 only,
  ``mtxfile_fwrite_mpi_double``).
"""

from __future__ import annotations

import numpy as np


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               local_device_ids=None) -> None:
    """Start the multi-controller runtime (the ``MPI_Init`` analog).

    Idempotent: a second call (or a call in an already-initialised
    process) is a no-op, so library code may call this unconditionally.
    With no arguments, JAX auto-detects cluster configuration from the
    TPU pod metadata / cluster-scheduler environment; the explicit
    arguments exist for manual launches and the CPU-based smoke test.
    """
    import jax

    from acg_tpu._platform import distributed_initialized

    if distributed_initialized():
        return
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)


def is_primary() -> bool:
    """True on the process that should write user-facing output."""
    import jax

    return jax.process_index() == 0


def put_global(arr, sharding):
    """Place a host array, identically present on every process, onto
    ``sharding`` (which may span devices of other processes).

    Single-process this is ``jax.device_put``.  Multi-process it builds
    the global array from per-process local shards -- every process holds
    the full host array (the driver reads/partitions the matrix on every
    controller, the analog of the reference's root-rank read + scatter,
    ``acggraph_scatter``), so the callback just slices it.
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    # dtype must be explicit: a process whose devices are all outside the
    # mesh holds no addressable shards to infer it from
    try:
        return jax.make_array_from_callback(arr.shape, sharding,
                                            lambda idx: arr[idx],
                                            dtype=arr.dtype)
    except TypeError:
        # older jax: no dtype kwarg -- inference from the local shards
        # still covers every process that addresses part of the mesh
        return jax.make_array_from_callback(arr.shape, sharding,
                                            lambda idx: arr[idx])


def get_global(x) -> np.ndarray:
    """Fetch a (possibly non-fully-addressable) device array to every
    host as a numpy array -- the ``MPI_Allgatherv`` of the solution
    vector in reverse (`mtxfile.h:1087` writes rank-by-rank instead; on
    a single-controller the assembled array is the natural form)."""
    import jax

    if jax.process_count() == 1 or x.is_fully_addressable:
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
