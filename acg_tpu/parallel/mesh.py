"""Device mesh construction for the solve.

The reference's communicator setup (``acgcomm_init_*``, rank = part id,
``cuda/acg-cuda.c:1036``) maps on TPU to a 1-D `jax.sharding.Mesh` whose
single axis enumerates subdomains: part p lives on mesh coordinate p.  The
mesh takes the role of the communicator; XLA inserts the collectives
(SURVEY.md section 2, "Distributed communication backend").

Multi-host topologies (the ICI/DCN split): after
`acg_tpu.parallel.multihost.initialize` (the MPI_Init analog),
``jax.devices()`` is the *global* device list, so the default mesh below
already spans all hosts; array ingress/egress go through
``multihost.put_global`` / ``get_global``.  Validated by a 2-process
gloo-backed CPU smoke test (``tests/test_multihost.py``).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

PARTS_AXIS = "parts"


def solve_mesh(nparts: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh of ``nparts`` devices with axis name ``parts``.

    With ``nparts`` greater than the device count this raises -- the
    reference equivalent is launching more MPI ranks than GPUs, which it
    also treats as a configuration error.

    Multi-controller with ``nparts`` below the global device count:
    devices are drawn round-robin across processes (not ``devices[:n]``,
    which would leave later hosts outside the mesh entirely), so every
    controller keeps at least one mesh device as long as
    ``nparts >= process_count``.  Below that there is no valid layout --
    the reference analog is launching MPI on fewer hosts, so we say so.
    """
    if devices is None:
        devices = jax.devices()
        if jax.process_count() > 1:
            by_proc: dict[int, list] = {}
            for d in devices:
                by_proc.setdefault(d.process_index, []).append(d)
            groups = [by_proc[p] for p in sorted(by_proc)]
            devices = [g[i] for i in range(max(map(len, groups)))
                       for g in groups if i < len(g)]
    if nparts is None:
        nparts = len(devices)
    if nparts > len(devices):
        raise ValueError(
            f"need {nparts} devices for {nparts} parts, have {len(devices)}")
    chosen = list(devices[:nparts])
    procs = {getattr(d, "process_index", 0) for d in chosen}
    import jax as _jax
    if len(procs) < _jax.process_count():
        raise ValueError(
            f"{nparts} parts cannot span all {_jax.process_count()} "
            f"controller processes; launch at most {nparts} controllers "
            f"(the MPI analog: fewer ranks than hosts)")
    return Mesh(np.array(chosen), (PARTS_AXIS,))
