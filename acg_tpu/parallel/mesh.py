"""Device mesh construction for the solve.

The reference's communicator setup (``acgcomm_init_*``, rank = part id,
``cuda/acg-cuda.c:1036``) maps on TPU to a 1-D `jax.sharding.Mesh` whose
single axis enumerates subdomains: part p lives on mesh coordinate p.  The
mesh takes the role of the communicator; XLA inserts the collectives
(SURVEY.md section 2, "Distributed communication backend").

Multi-host topologies (the ICI/DCN split) need no code change here: the
caller passes the global device list and JAX's standard multi-controller
runtime shards the same program.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

PARTS_AXIS = "parts"


def solve_mesh(nparts: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh of ``nparts`` devices with axis name ``parts``.

    With ``nparts`` greater than the device count this raises -- the
    reference equivalent is launching more MPI ranks than GPUs, which it
    also treats as a configuration error.
    """
    if devices is None:
        devices = jax.devices()
    if nparts is None:
        nparts = len(devices)
    if nparts > len(devices):
        raise ValueError(
            f"need {nparts} devices for {nparts} parts, have {len(devices)}")
    return Mesh(np.array(devices[:nparts]), (PARTS_AXIS,))
