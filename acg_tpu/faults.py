"""Deterministic fault injection for solver-resilience testing.

The reference suite ships no fault injection; this module supplies the
missing tier for the TPU build (round-5 verdict: "race detection/
elasticity/fault injection: none").  A single seed-driven spec -- from
the ``--fault-inject`` CLI flag, the ``ACG_TPU_FAULT_INJECT`` env var
(which subprocess children inherit, so multi-process scenarios need no
plumbing), or :func:`install` in tests -- selects ONE fault site and
firing condition:

  ``SITE:MODE[@ITER][:KEY=VAL]...``

  * ``spmv:nan@7``          NaN into the SpMV output at iteration 7
  * ``spmv:inf@7:part=2``   Inf into part 2's local SpMV result
  * ``halo:nan@3``          NaN into the received halo payload
  * ``dot:neg@5``           (p, Ap) driven non-positive at iteration 5
  * ``precond:nan@4``       NaN into z = M^-1 r at iteration 4 (the
                            non-SPD-preconditioner breakdown path;
                            needs an armed --precond)
  * ``dot:nan@5``           NaN into the dot scalar
  * ``sdc:flip@7``          SIGN-FLIP one SpMV output element (finite:
                            invisible to the non-finite guards, caught
                            only by the ABFT checksum test, --abft)
  * ``crash:exit@20``       hard os._exit once a checkpointed solve
                            crosses 20 iterations (needs --ckpt)
  * ``peer:dead:proc=1``    controller 1 dies before its next
                            error-agreement checkpoint
  * ``peer:stall:proc=1:secs=30``  controller 1 stalls instead
  * ``backend:hang:secs=120``      backend init (probe children) hangs
  * ``solve:slow@10:secs=0.05``    every solve from soak index 10 on is
                                   dilated 50 ms (drift-detector test)

Keys: ``part`` (mesh part a vector fault targets; -1 = every part),
``proc`` (controller index for peer faults), ``secs`` (hang/stall
duration), ``seed`` (picks the poisoned element deterministically).

Device-site faults (``spmv``/``dot``/``halo``) are applied INSIDE the
jitted solve loops: :class:`FaultSpec` is hashable and rides the
programs' static arguments, so an armed injector compiles its own cache
entry and a disarmed run compiles byte-identical code to a build without
this module.  The ``apply_*`` helpers are pure jnp functions of the
carried iteration index; numpy twins serve the eager host solver.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time

import numpy as np

DEVICE_SITES = ("spmv", "dot", "halo", "precond", "sdc")
_SITES = DEVICE_SITES + ("peer", "backend", "solve", "crash")
_MODES = {
    "spmv": ("nan", "inf"),
    "halo": ("nan", "inf"),
    # silent data corruption in the SpMV output: ONE element's sign is
    # flipped at the armed iteration -- a finite value, so the
    # non-finite breakdown guards can NEVER catch it; only the ABFT
    # checksum test (acg_tpu.health, --abft) detects it on device
    "sdc": ("flip",),
    # host-side hard process death between checkpoint chunks
    # (``crash:exit@K``: os._exit once the chunked solve crosses K
    # total iterations) -- the --ckpt/--resume survivability test
    # vector; refuses without an armed checkpoint (it could never fire)
    "crash": ("exit",),
    # the preconditioner apply's output z = M^-1 r (PCG tier,
    # acg_tpu.precond): a poisoned z drives the (r, z) scalar non-finite
    # or negative -- the non-SPD-M breakdown path, made deterministic
    "precond": ("nan", "inf"),
    "dot": ("nan", "zero", "neg"),
    "peer": ("dead", "stall"),
    "backend": ("hang",),
    # host-side latency dilation for the soak driver's drift detector
    # (``solve:slow@K:secs=S``: every solve from index K onward sleeps
    # S seconds inside the timed window) -- contention/throttling made
    # deterministic; the compiled programs are untouched
    "solve": ("slow",),
}
ENV_VAR = "ACG_TPU_FAULT_INJECT"


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One parsed fault: immutable and hashable (a jit static arg)."""

    site: str
    mode: str
    iteration: int = -1   # device sites: the 0-based iteration to fire at
    part: int = -1        # mesh part a vector fault targets (-1 = all)
    proc: int = 0         # controller index for peer faults
    secs: float = 300.0   # hang/stall duration
    seed: int = 0         # picks the poisoned element index

    @property
    def device_site(self) -> bool:
        return self.site in DEVICE_SITES

    def __str__(self) -> str:
        """The canonical ``SITE:MODE[@ITER][:KEY=VAL]`` spec string:
        ``parse_fault_spec(str(spec)) == spec``, so snapshot metadata
        and the chaos ledger record re-runnable specs instead of
        dataclass reprs."""
        s = f"{self.site}:{self.mode}"
        if self.iteration >= 0:
            s += f"@{self.iteration}"
        if self.part >= 0:
            s += f":part={self.part}"
        if self.proc != 0:
            s += f":proc={self.proc}"
        if self.secs != 300.0:
            s += f":secs={self.secs:g}"
        if self.seed != 0:
            s += f":seed={self.seed}"
        return s

    def shift(self, consumed: int) -> "FaultSpec | None":
        """The spec as seen by a RESTARTED solve that already ran
        ``consumed`` iterations: the firing iteration moves earlier, and
        a fault that already fired vanishes (None) -- restarts must not
        deterministically re-trigger the same breakdown forever."""
        if not self.device_site:
            return self
        it = self.iteration - int(consumed)
        if it < 0:
            return None
        return dataclasses.replace(self, iteration=it)

    # -- device-side application (inside jit; self is static) -----------

    def _fire(self, k, part_index=None):
        import jax.numpy as jnp

        fire = jnp.asarray(k) == self.iteration
        if part_index is not None and self.part >= 0:
            fire = fire & (jnp.asarray(part_index) == self.part)
        return fire

    def _poison(self, y, k, part_index):
        import jax.numpy as jnp

        bad = jnp.asarray(jnp.nan if self.mode == "nan" else jnp.inf,
                          y.dtype)
        idx = self.seed % max(int(y.shape[0]), 1)
        return jnp.where(self._fire(k, part_index), y.at[idx].set(bad), y)

    def apply_spmv(self, y, k, part_index=None):
        """Poison one element of an SpMV output at the armed iteration.
        ``sdc:flip`` flips the element's SIGN instead of writing a
        non-finite -- bit-level corruption the finiteness guards are
        blind to (the ABFT test vector)."""
        if k is None:
            return y
        if self.site == "sdc":
            import jax.numpy as jnp

            idx = self.seed % max(int(y.shape[0]), 1)
            return jnp.where(self._fire(k, part_index),
                             y.at[idx].set(-y[idx]), y)
        if self.site != "spmv":
            return y
        return self._poison(y, k, part_index)

    def apply_halo(self, ghost, k, part_index=None):
        """Poison one element of the received halo payload."""
        if self.site != "halo" or k is None:
            return ghost
        return self._poison(ghost, k, part_index)

    def apply_precond(self, z, k, part_index=None):
        """Poison one element of the preconditioner apply's output."""
        if self.site != "precond" or k is None:
            return z
        return self._poison(z, k, part_index)

    def apply_dot(self, s, k):
        """Corrupt a CG scalar: NaN, zero, or driven non-positive."""
        if self.site != "dot" or k is None:
            return s
        import jax.numpy as jnp

        if self.mode == "nan":
            bad = jnp.asarray(jnp.nan, s.dtype)
        elif self.mode == "zero":
            bad = jnp.zeros_like(s)
        else:  # neg: guaranteed non-positive whatever the true value
            bad = -jnp.abs(s) - jnp.asarray(1, s.dtype)
        return jnp.where(self._fire(k), bad, s)

    # -- host-side application (eager numpy) ----------------------------

    def apply_spmv_np(self, y: np.ndarray, k: int) -> np.ndarray:
        if self.site not in ("spmv", "sdc") or k != self.iteration:
            return y
        y = np.array(y, copy=True)
        idx = self.seed % max(y.size, 1)
        if self.site == "sdc":
            y[idx] = -y[idx]
        else:
            y[idx] = np.nan if self.mode == "nan" else np.inf
        return y

    def apply_precond_np(self, z: np.ndarray, k: int) -> np.ndarray:
        if self.site != "precond" or k != self.iteration:
            return z
        z = np.array(z, copy=True)
        z[self.seed % max(z.size, 1)] = (np.nan if self.mode == "nan"
                                         else np.inf)
        return z

    def apply_dot_np(self, s: float, k: int) -> float:
        if self.site != "dot" or k != self.iteration:
            return s
        if self.mode == "nan":
            return float("nan")
        if self.mode == "zero":
            return 0.0
        return -abs(s) - 1.0


def parse_fault_spec(text: str) -> FaultSpec:
    """Parse the ``SITE:MODE[@ITER][:KEY=VAL]...`` grammar; raises
    ``ValueError`` with the offending token named."""
    fields = [f for f in str(text).strip().split(":") if f]
    if len(fields) < 2:
        raise ValueError(
            f"fault spec {text!r}: expected SITE:MODE[@ITER][:KEY=VAL]")
    site = fields[0]
    mode = fields[1]
    kwargs: dict = {}
    if "@" in mode:
        mode, _, it = mode.partition("@")
        try:
            kwargs["iteration"] = int(it)
        except ValueError:
            raise ValueError(f"fault spec {text!r}: bad iteration {it!r}")
    if site not in _SITES:
        raise ValueError(f"fault spec {text!r}: unknown site {site!r} "
                         f"(one of {', '.join(_SITES)})")
    if mode not in _MODES[site]:
        raise ValueError(f"fault spec {text!r}: unknown mode {mode!r} for "
                         f"site {site!r} (one of {', '.join(_MODES[site])})")
    for kv in fields[2:]:
        key, eq, val = kv.partition("=")
        if not eq or key not in ("part", "proc", "secs", "seed"):
            raise ValueError(f"fault spec {text!r}: bad key {kv!r} "
                             f"(part=, proc=, secs=, seed=)")
        try:
            kwargs[key] = float(val) if key == "secs" else int(val)
        except ValueError:
            raise ValueError(f"fault spec {text!r}: bad value {kv!r}")
    if site in DEVICE_SITES + ("crash",) and "iteration" not in kwargs:
        raise ValueError(f"fault spec {text!r}: site {site!r} needs a "
                         f"firing iteration (e.g. {site}:{mode}@5)")
    if site == "solve" and "secs" not in kwargs:
        # the default 300 s stall is a hang-detection figure; a latency
        # dilation without an explicit magnitude is a footgun
        raise ValueError(f"fault spec {text!r}: solve:slow needs an "
                         f"explicit dilation (e.g. solve:slow@10:"
                         f"secs=0.05)")
    return FaultSpec(site=site, mode=mode, **kwargs)


_installed: FaultSpec | None = None
_suppressed: bool = False


@contextlib.contextmanager
def suppressed():
    """Temporarily disarm the injector (env var included): the recovery
    ladder's fallback rungs run under this -- the injected fault models
    the ACCELERATED path's failure, and re-firing it inside the host
    oracle would poison the very rung that exists to survive it."""
    global _suppressed
    prev = _suppressed
    _suppressed = True
    try:
        yield
    finally:
        _suppressed = prev


def install(spec: FaultSpec | None) -> None:
    """Arm (or with None, disarm) the process-wide injector."""
    global _installed
    _installed = spec


@contextlib.contextmanager
def injected(spec: FaultSpec | str):
    """Context manager for tests: arm ``spec`` inside the block."""
    if isinstance(spec, str):
        spec = parse_fault_spec(spec)
    prev = _installed
    install(spec)
    try:
        yield spec
    finally:
        install(prev)


def active_fault() -> FaultSpec | None:
    """The armed spec: :func:`install` wins, else ``ACG_TPU_FAULT_INJECT``
    (parsed fresh each call -- subprocess tests mutate the environment).
    A malformed env spec raises a typed AcgError (INVALID_VALUE) naming
    the variable -- this is read lazily deep inside solves, where a raw
    ValueError would dodge every caller's error handling."""
    if _suppressed:
        return None
    if _installed is not None:
        return _installed
    env = os.environ.get(ENV_VAR)
    if not env:
        return None
    try:
        return parse_fault_spec(env)
    except ValueError as e:
        from acg_tpu.errors import AcgError, ErrorCode

        raise AcgError(ErrorCode.INVALID_VALUE, f"{ENV_VAR}: {e}")


def device_fault() -> FaultSpec | None:
    """The armed spec when it targets a device site, else None -- what
    the solvers thread into their compiled programs (peer/backend faults
    must not perturb the compiled solve)."""
    spec = active_fault()
    return spec if spec is not None and spec.device_site else None


def maybe_fail_peer(stage: str = "") -> None:
    """Peer-fault hook for the error-agreement path: on the targeted
    controller, ``peer:dead`` exits hard BEFORE the checkpoint (the
    surviving controllers' watchdog must abort them within the agreed
    timeout) and ``peer:stall`` sleeps through it."""
    spec = active_fault()
    if spec is None or spec.site != "peer":
        return
    import jax

    if jax.process_index() != spec.proc:
        return
    import sys

    if spec.mode == "dead":
        from acg_tpu.errors import ExitCode

        sys.stderr.write(f"acg-tpu: fault injector: controller "
                         f"{spec.proc} dying before checkpoint "
                         f"{stage or '?'}\n")
        sys.stderr.flush()
        os._exit(int(ExitCode.PEER_DEAD_INJECTED))
    sys.stderr.write(f"acg-tpu: fault injector: controller {spec.proc} "
                     f"stalling {spec.secs:.0f}s at checkpoint "
                     f"{stage or '?'}\n")
    sys.stderr.flush()
    time.sleep(spec.secs)


def maybe_slow_solve(solve_index: int) -> float:
    """Soak-driver hook (``solve:slow@K:secs=S``): sleep ``S`` seconds
    inside the timed window of every solve from index ``K`` onward
    (``@ITER`` here is a SOLVE index, not an iteration -- the drift
    detector needs a clean baseline window first).  Returns the seconds
    slept so callers can log the dilation."""
    spec = active_fault()
    if spec is None or spec.site != "solve":
        return 0.0
    start = max(spec.iteration, 0)
    if int(solve_index) < start:
        return 0.0
    time.sleep(spec.secs)
    return spec.secs


def maybe_crash(before: int, after: int) -> None:
    """Checkpoint-chunk hook (``crash:exit@K``): hard ``os._exit`` the
    first time the chunked solve CROSSES K total iterations -- i.e.
    ``before < K <= after``, where ``before``/``after`` are the
    cumulative iteration counts around one chunk.  Crossing (not
    threshold) semantics matter for ``--resume``: a resumed solve
    starts at the last snapshot, which already lies at-or-past K, so
    the same inherited spec does not re-kill the relaunch.  Fires
    AFTER the chunk's snapshot committed (the chunk drivers call this
    right after their atomic write), modelling preemption between
    iterations."""
    spec = active_fault()
    if spec is None or spec.site != "crash":
        return
    K = max(int(spec.iteration), 0)
    if not (int(before) < K <= int(after)):
        return
    import sys

    from acg_tpu.checkpoint import CRASH_EXIT_CODE

    sys.stderr.write(f"acg-tpu: fault injector: hard exit at "
                     f"{int(after)} iterations (crash:exit@{K})\n")
    sys.stderr.flush()
    os._exit(CRASH_EXIT_CODE)


def maybe_hang_backend() -> None:
    """Backend-fault hook for probe children: ``backend:hang`` sleeps in
    place of the backend init, so tunnel-down behaviour (a wedged
    ``jax.devices()``) is reproducible without a tunnel."""
    spec = active_fault()
    if spec is not None and spec.site == "backend" and spec.mode == "hang":
        time.sleep(spec.secs)
