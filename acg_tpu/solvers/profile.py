"""Per-op device timing -- the reference's ``ACG_ENABLE_PROFILING`` tier.

The reference brackets every GPU op with CUDA event pairs
(``acgEventRecord``, ``cgcuda.c:73-76``; event arrays ``:585-610``;
summed post-solve ``:1057-1095``) and reports per-op seconds and GB/s in
the stats block (``:1942-1957``).  Under XLA the whole solve is ONE
compiled program -- bracketing ops inside it would break the fusion that
makes it fast -- so this tier *replays* each op class standalone on the
solver's own device-resident arrays (median of ``reps`` timed calls
after compile + warmup) and scales by the op counts the always-on
counters already track.

Honest caveats, also noted in the stats block docs:
  * replay times are per-op upper bounds: in the real loop XLA fuses
    vector updates into neighbouring ops, so the per-op sum can exceed
    ``tsolve`` (the surplus appears as negative "other" time -- itself a
    measure of how much fusion saves);
  * the distributed ``gemv`` replay includes the overlapped halo
    exchange (they are one fused program by design); the halo is also
    measured alone so the overlap benefit is visible by comparison.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def _median_time(fn, *args, reps: int = 10) -> float:
    reps = max(int(reps), 1)
    jax.block_until_ready(fn(*args))  # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def profile_ops(solver, b, reps: int = 10) -> dict[str, float]:
    """Fill ``solver.stats.ops[*].t`` with replayed per-op device times.

    Returns ``{op: seconds_per_call}`` for the measured op classes.
    Dispatches on solver type; host solvers already time ops for real
    (eager mode) and are returned unchanged.
    """
    # unwrap mixed-precision refinement down to the device solver
    while hasattr(solver, "inner"):
        solver = solver.inner

    from acg_tpu.parallel.dist import DistCGSolver
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    if isinstance(solver, JaxCGSolver):
        per_call = _profile_single(solver, b, reps)
    elif isinstance(solver, DistCGSolver):
        per_call = _profile_dist(solver, b, reps)
    else:
        return {}

    for op, t in per_call.items():
        s = solver.stats.ops[op]
        s.t = t * s.n
    return per_call


def _profile_single(solver, b, reps: int) -> dict[str, float]:
    from acg_tpu.solvers.jax_cg import _spmv_fn

    A = solver.A
    dtype = (A.dtype if hasattr(A, "dtype")
             else A.data.dtype if hasattr(A, "data") else A.vals.dtype)
    x = jnp.asarray(np.asarray(b), dtype=dtype)
    spmv_f = _spmv_fn(solver.kernels)
    if solver.precise_dots:
        from acg_tpu.ops.precision import dot_compensated

        def _dot(a, c):
            hi, lo = dot_compensated(a, c)
            return hi + lo
    else:
        _dot = jnp.dot
    gemv = jax.jit(lambda v: spmv_f(A, v))
    dot = jax.jit(_dot)
    axpy = jax.jit(lambda y, a, p: y + a * p)
    alpha = jnp.asarray(0.5, dtype)
    return {
        "gemv": _median_time(gemv, x, reps=reps),
        "dot": _median_time(dot, x, x, reps=reps),
        "axpy": _median_time(axpy, x, alpha, x, reps=reps),
    }


def _profile_dist(solver, b, reps: int) -> dict[str, float]:
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from acg_tpu.parallel.dist import make_dist_spmv
    from acg_tpu.parallel.halo import halo_exchange
    from acg_tpu.parallel.halo_dma import halo_exchange_dma
    from acg_tpu.parallel.mesh import PARTS_AXIS

    prob = solver.problem
    mesh = solver.mesh
    axis = PARTS_AXIS
    pspec, rspec = P(PARTS_AXIS), P()
    bd, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = solver.device_args(b)
    spmv_shard = make_dist_spmv(prob, solver.comm, solver._interpret,
                                kernels=solver.kernels)

    def smap(body, in_specs, out_specs):
        return jax.jit(jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                                     out_specs=out_specs, check_vma=False))

    # distributed SpMV (includes the overlapped halo, by design)
    def gemv_body(la, ga, sidx, gsrc, gval, scnt, rcnt, x):
        la, ga = (jax.tree.map(lambda a: a[0], t) for t in (la, ga))
        sidx, gsrc, gval, scnt, rcnt, x = (
            a[0] for a in (sidx, gsrc, gval, scnt, rcnt, x))
        return spmv_shard(x, la, ga, sidx, gsrc, gval, scnt, rcnt)[None]

    gemv = smap(gemv_body, (pspec,) * 8, pspec)
    out = {"gemv": _median_time(
        gemv, la, ga, sidx, gsrc, gval, scnt, rcnt, bd, reps=reps)}

    # halo exchange alone (reference times it per exchange, halo.h:176-186)
    if prob.halo.has_ghosts:
        if solver.comm == "dma":
            interpret = solver._interpret

            def halo_body(x, sidx, gsrc, gval, scnt, rcnt):
                return halo_exchange_dma(x[0], sidx[0], gsrc[0], gval[0],
                                         scnt[0], rcnt[0], axis,
                                         interpret=interpret)[None]

            halo = smap(halo_body, (pspec,) * 6, pspec)
            out["halo"] = _median_time(halo, bd, sidx, gsrc, gval, scnt,
                                       rcnt, reps=reps)
        else:
            def halo_body(x, sidx, gsrc):
                return halo_exchange(x[0], sidx[0], gsrc[0], axis)[None]

            halo = smap(halo_body, (pspec,) * 3, pspec)
            out["halo"] = _median_time(halo, bd, sidx, gsrc, reps=reps)

    # local dot (no reduction) and the scalar allreduce, separately --
    # the reference's cublasDdot + acgcomm_allreduce split
    def dot_body(a, c):
        return jnp.dot(a[0], c[0])[None]

    dot = smap(dot_body, (pspec, pspec), pspec)
    out["dot"] = _median_time(dot, bd, bd, reps=reps)

    def psum_body(s):
        return lax.psum(s[0], axis)

    from acg_tpu.parallel.multihost import put_global

    pair = put_global(np.zeros((prob.nparts, 2), dtype=prob.dtype),
                      jax.sharding.NamedSharding(mesh, pspec))
    allreduce = smap(psum_body, (pspec,), rspec)
    out["allreduce"] = _median_time(allreduce, pair, reps=reps)

    axpy = jax.jit(lambda y, a, p: y + a * p)
    out["axpy"] = _median_time(axpy, bd, jnp.asarray(0.5, prob.dtype), bd,
                               reps=reps)
    return out
