"""Per-op device timing -- the reference's ``ACG_ENABLE_PROFILING`` tier.

The reference brackets every GPU op with CUDA event pairs
(``acgEventRecord``, ``cgcuda.c:73-76``; event arrays ``:585-610``;
summed post-solve ``:1057-1095``) and reports per-op seconds and GB/s in
the stats block (``:1942-1957``).  Under XLA the whole solve is ONE
compiled program -- bracketing ops inside it would break the fusion that
makes it fast -- so this tier *replays* each op class standalone on the
solver's own device-resident arrays (best-of-``reps`` timings of
chained in-program repetitions, see below) and scales by the op counts
the always-on counters already track.

Honest caveats, also noted in the stats block docs:
  * replay times are per-op upper bounds: in the real loop XLA fuses
    vector updates into neighbouring ops, so the per-op sum can exceed
    ``tsolve`` (the surplus appears as negative "other" time -- itself a
    measure of how much fusion saves);
  * the distributed ``gemv`` replay includes the overlapped halo
    exchange (they are one fused program by design); the halo is also
    measured alone so the overlap benefit is visible by comparison;
  * per-program dispatch latency on remote/tunneled chips reaches
    ~100 ms under load -- orders beyond the ops themselves -- and
    fluctuates by tens of ms, so each op is measured as the DIFFERENCE
    between two chained programs (4*INNER vs INNER in-program
    repetitions): the dispatch term cancels instead of being estimated.
    The raw dispatch latency is returned under ``"dispatch"`` for
    context (the in-loop ops pay it once per solve, not once per op);
  * chaining a scalar-result op (dot, halo, allreduce) requires folding
    its result back into the carried vector to keep the data
    dependence, which adds ~one vector read+write per repetition --
    those entries are therefore upper bounds by roughly one
    axpy-equivalent (reported alongside, so readers can discount it);
  * a ``--trace`` capture SUPERSEDES this tier where it can: the CLI
    applies :func:`acg_tpu.tracing.apply_measured_ops` after the
    replay, so any op class the profiler resolved to real device
    events (TPU captures carry per-HLO-op timelines) reports MEASURED
    seconds instead of the replayed estimate, and the stats block's
    ``tracing: ops_source`` line says which rows were replaced.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


# op repetitions chained INSIDE one jitted program: per-call dispatch
# latency (~100 ms on a loaded tunnel, and itself fluctuating by tens
# of ms) is paid once per program, so the op cost is recovered from the
# DIFFERENCE between a 4*INNER-iteration chain and an INNER-iteration
# chain -- the dispatch term cancels.  Chains carry a data dependence
# so XLA cannot elide them.
INNER = 64


def _best_time(fn, *args, reps: int = 10) -> float:
    reps = max(int(reps), 1)
    from acg_tpu._platform import device_sync

    device_sync(jax.tree_util.tree_leaves(fn(*args))[0])  # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        device_sync(jax.tree_util.tree_leaves(fn(*args))[0])
        ts.append(time.perf_counter() - t0)
    # min: on a shared chip contention bursts inflate most samples; the
    # fastest run is the uncontended estimate (same estimator as bench)
    return min(ts)


def _chain(op, inner, x0, *extra):
    """jit(fori_loop) chaining ``inner`` applications of ``op`` through
    its first argument (op must preserve that argument's shape)."""
    def run(x, *e):
        return jax.lax.fori_loop(0, inner, lambda _, y: op(y, *e), x)

    return jax.jit(run), (x0, *extra)


def _time_op(op, x0, *extra, reps: int = 10) -> float:
    """Two-point amortised estimate of one op application's seconds."""
    lo_fn, args = _chain(op, INNER, x0, *extra)
    hi_fn, _ = _chain(op, 4 * INNER, x0, *extra)
    lo = _best_time(lo_fn, *args, reps=reps)
    hi = _best_time(hi_fn, *args, reps=reps)
    return max(hi - lo, 0.0) / (3 * INNER)


def profile_ops(solver, b, reps: int = 10) -> dict[str, float]:
    """Fill ``solver.stats.ops[*].t`` with replayed per-op device times.

    Returns ``{op: seconds_per_call}`` for the measured op classes.
    Dispatches on solver type; host solvers already time ops for real
    (eager mode) and are returned unchanged.
    """
    # unwrap mixed-precision refinement down to the device solver
    while hasattr(solver, "inner"):
        solver = solver.inner

    from acg_tpu.parallel.dist import DistCGSolver
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    if isinstance(solver, JaxCGSolver):
        per_call = _profile_single(solver, b, reps)
    elif isinstance(solver, DistCGSolver):
        per_call = _profile_dist(solver, b, reps)
    else:
        return {}

    for op, t in per_call.items():
        s = solver.stats.ops[op]
        s.t = t * s.n
    # the scalar-chain replay caveat as a NUMBER, not prose (the module
    # docstring's last bullet): chaining a scalar-result op (dot, nrm2,
    # halo, allreduce) folds its scalar back into the carried vector to
    # keep the data dependence, ~one axpy-equivalent extra per
    # repetition -- so those entries are upper bounds by about this
    # much per call.  Reported as an explicit key so consumers can
    # discount it mechanically instead of reading a docstring.
    per_call["chain_overhead"] = per_call.get("axpy", 0.0)
    # per-program dispatch latency, reported for context (the in-loop
    # ops pay it once per solve, not once per op).  The noop rides the
    # SOLVER'S value dtype, not the default: under x64 the default
    # would dispatch an f64 program while the solve runs f32 (and
    # vice versa for bf16 tiers) -- the measurement must match the
    # solve's programs
    vdt = _value_dtype(solver)
    noop = jax.jit(lambda v: v + jnp.asarray(1, v.dtype))
    per_call["dispatch"] = _best_time(noop, jnp.zeros((8,), vdt),
                                      reps=reps)
    return per_call


def _value_dtype(solver):
    """The dtype of the solve's VECTORS (they differ from the matrix
    dtype under --dtype mixed; replacement solves run f32 outer)."""
    import numpy as _np

    if getattr(solver, "replace_every", 0):
        return jnp.float32
    vdt = getattr(solver, "vector_dtype", None)
    if vdt is not None:
        return jnp.dtype(vdt)
    prob = getattr(solver, "problem", None)
    if prob is not None:
        return jnp.dtype(prob.vdtype)
    A = solver.A
    dt = (A.dtype if hasattr(A, "dtype")
          else A.data.dtype if hasattr(A, "data")
          else A.vals.dtype if hasattr(A, "vals")  # CooMatrix
          else _np.float32)
    return jnp.dtype(dt)


def _profile_single(solver, b, reps: int) -> dict[str, float]:
    from acg_tpu.solvers.jax_cg import _spmv_fn

    # the matrix the PROGRAMS consume: for the pallas-roll tier this is
    # the per-shard-padded twin its callable kernel expects (the clean
    # solver.A would feed it mis-shaped planes)
    A = solver._A_program
    dtype = (A.dtype if hasattr(A, "dtype")
             else A.data.dtype if hasattr(A, "data") else A.vals.dtype)
    # b may already live on device (gen-direct path): no host round-trip
    x = jnp.asarray(b, dtype=dtype)
    # the fused tier's gemv replay uses the closest standalone kernel
    # (its phase kernels have no standalone-SpMV form); callable tiers
    # (PallasRollSpmv) pass through _spmv_fn unchanged
    spmv_f = _spmv_fn("pallas" if (isinstance(solver.kernels, str)
                                   and solver.kernels.startswith("fused"))
                      else solver.kernels)
    if solver.precise_dots:
        from acg_tpu.ops.precision import dot_compensated

        def _dot(a, c):
            hi, lo = dot_compensated(a, c)
            return hi + lo
    else:
        _dot = jnp.dot
    # chains: gemv feeds y back as x (square A); dot folds its scalar
    # into the next input (unfoldable data dependence); axpy chains y
    alpha = jnp.asarray(0.5, dtype)
    tiny = jnp.asarray(1e-30, dtype)
    # the matrix rides as an ARGUMENT, not a closure: captured device
    # arrays become compile-time constants and are shipped with the
    # program (gigabytes at large N)
    out = {
        "gemv": _time_op(lambda v, M: spmv_f(M, v), x, A, reps=reps),
        "dot": _time_op(lambda v, c: v + tiny * _dot(v, c), x, x,
                        reps=reps),
        # the convergence test's (r, r): one vector read (vs the dot
        # class's two) -- its counters are now filled analytically by
        # the solvers, so the replay must price it too
        "nrm2": _time_op(lambda v: v + tiny * _dot(v, v), x, reps=reps),
        "axpy": _time_op(lambda y, a, p: y + a * p, x, alpha, x,
                         reps=reps),
        # copy (p = r at setup): one read + one write; a scale by ~1
        # keeps the chain's data dependence where a literal jnp.copy
        # would be elided inside the fused chain
        "copy": _time_op(lambda y, a: y * a, x,
                         jnp.asarray(1.0000001, dtype), reps=reps),
    }
    spec = getattr(solver, "precond_spec", None)
    if spec is not None:
        # replay the M^-1 apply too: the analytic precond counters
        # must not print 0 seconds next to replayed times (the could-
        # never-fire discipline).  The replayed seconds are divided by
        # the per-apply op count (cheby counts its degree-many SpMVs),
        # so ops["precond"].t = seconds/op x n reconstructs the true
        # per-apply cost
        from acg_tpu.precond import make_apply

        mstate = solver._ensure_precond_state()
        papply = make_apply(spec, spmv_f)
        per = spec.degree if spec.kind == "cheby" else 1
        out["precond"] = _time_op(
            lambda v, M, ms: papply(ms, M, v), x, A, mstate,
            reps=reps) / per
    return out


def _profile_dist(solver, b, reps: int) -> dict[str, float]:
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from acg_tpu.parallel.dist import make_dist_spmv
    from acg_tpu.parallel.halo import halo_exchange
    from acg_tpu.parallel.halo_dma import halo_exchange_dma
    from acg_tpu.parallel.mesh import PARTS_AXIS

    prob = solver.problem
    mesh = solver.mesh
    axis = PARTS_AXIS
    pspec = P(PARTS_AXIS)
    bd, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = solver.device_args(b)
    if str(solver.kernels).startswith("fused"):
        # the fused tier's device_args extends ga with the interior
        # row lists; replay the SAME overlapped SpMV the solve runs
        from acg_tpu.parallel.dist import make_dist_spmv_overlapped
        spmv_shard = make_dist_spmv_overlapped(prob, solver.comm,
                                               solver._interpret)
    else:
        spmv_shard = make_dist_spmv(prob, solver.comm, solver._interpret,
                                    kernels=solver.kernels)

    tiny = jnp.asarray(1e-30, prob.vdtype)

    from acg_tpu._platform import shard_map as _shard_map

    def smap(body, in_specs):
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=pspec)

    # every op is expressed as x -> x' (shape/sharding preserved) so
    # _chain can amortise INNER executions inside one program; scalarish
    # results fold back through `tiny` to keep the data dependence
    # matrix blocks ride as ARGUMENTS (captured device arrays become
    # compile-time constants shipped with the program)
    def gemv_once(x, la, ga, sidx, gsrc, gval, scnt, rcnt):
        def body(la, ga, sidx, gsrc, gval, scnt, rcnt, x):
            la, ga = (jax.tree.map(lambda a: a[0], t) for t in (la, ga))
            sidx, gsrc, gval, scnt, rcnt, x = (
                a[0] for a in (sidx, gsrc, gval, scnt, rcnt, x))
            return spmv_shard(x, la, ga, sidx, gsrc, gval, scnt, rcnt)[None]

        return smap(body, (pspec,) * 8)(la, ga, sidx, gsrc, gval, scnt,
                                        rcnt, x)

    out = {"gemv": _time_op(gemv_once, bd, la, ga, sidx, gsrc, gval,
                            scnt, rcnt, reps=reps)}

    # halo exchange alone (reference times it per exchange, halo.h:176-186)
    if prob.halo.has_ghosts:
        if solver.comm == "dma":
            interpret = solver._interpret

            def halo_once(x, sidx, gsrc, gval, scnt, rcnt):
                def body(x, sidx, gsrc, gval, scnt, rcnt):
                    ghost = halo_exchange_dma(x[0], sidx[0], gsrc[0],
                                              gval[0], scnt[0], rcnt[0],
                                              axis, interpret=interpret)
                    return (x[0] + tiny * jnp.sum(ghost))[None]

                return smap(body, (pspec,) * 6)(x, sidx, gsrc, gval,
                                                scnt, rcnt)

            out["halo"] = _time_op(halo_once, bd, sidx, gsrc, gval,
                                   scnt, rcnt, reps=reps)
        else:
            def halo_once(x, sidx, gsrc):
                def body(x, sidx, gsrc):
                    ghost = halo_exchange(x[0], sidx[0], gsrc[0], axis)
                    return (x[0] + tiny * jnp.sum(ghost))[None]

                return smap(body, (pspec,) * 3)(x, sidx, gsrc)

            out["halo"] = _time_op(halo_once, bd, sidx, gsrc, reps=reps)

    # local dot (no reduction) and the scalar allreduce, separately --
    # the reference's cublasDdot + acgcomm_allreduce split
    def dot_once(x, c):
        def body(a, c):
            # two-vector dot (the loop's (p,t)/(r,r-after-update)
            # class): carried vector against a fixed second operand
            return (a[0] + tiny * jnp.dot(a[0], c[0]))[None]

        return smap(body, (pspec, pspec))(x, c)

    out["dot"] = _time_op(dot_once, bd, x0 + 1.0, reps=reps)

    def nrm2_once(x):
        def body(a):
            # single-vector read: the convergence test's (r, r) class
            return (a[0] + tiny * jnp.dot(a[0], a[0]))[None]

        return smap(body, (pspec,))(x)

    out["nrm2"] = _time_op(nrm2_once, bd, reps=reps)
    # copy (p = r at setup): one read + one write per part; the
    # scale-by-~1 keeps the chain's data dependence (like axpy below,
    # sharding propagates through the plain jit chain)
    out["copy"] = _time_op(lambda y, a: y * a, bd,
                           jnp.asarray(1.0000001, prob.vdtype), reps=reps)

    def allreduce_once(s):
        def body(s):
            return (s[0] + tiny * lax.psum(s[0], axis))[None]

        return smap(body, (pspec,))(s)

    from acg_tpu.parallel.multihost import put_global

    pair = put_global(np.zeros((prob.nparts, 2), dtype=prob.vdtype),
                      jax.sharding.NamedSharding(mesh, pspec))
    out["allreduce"] = _time_op(allreduce_once, pair, reps=reps)

    out["axpy"] = _time_op(lambda y, a, p: y + a * p, bd,
                           jnp.asarray(0.5, prob.vdtype), bd, reps=reps)

    spec = getattr(solver, "precond_spec", None)
    if spec is not None:
        # the sharded M^-1 apply (the single-device replay's twin):
        # jacobi/bjacobi run per shard with no communication, cheby
        # through the same halo'd SpMV the gemv replay times
        from acg_tpu.precond import make_apply

        mstate = solver._ensure_precond_state(
            (bd, x0, la, ga, sidx, gsrc, gval, scnt, rcnt))

        def precond_once(x, la, ga, sidx, gsrc, gval, scnt, rcnt, ms):
            def body(la, ga, sidx, gsrc, gval, scnt, rcnt, x, ms):
                la, ga = (jax.tree.map(lambda a: a[0], t)
                          for t in (la, ga))
                sidx, gsrc, gval, scnt, rcnt, x = (
                    a[0] for a in (sidx, gsrc, gval, scnt, rcnt, x))
                ms = jax.tree.map(lambda a: a[0], ms)
                papply = make_apply(
                    spec, lambda _A, v: spmv_shard(v, la, ga, sidx,
                                                   gsrc, gval, scnt,
                                                   rcnt))
                return papply(ms, None, x)[None]

            return smap(body, (pspec,) * 9)(la, ga, sidx, gsrc, gval,
                                            scnt, rcnt, x, ms)

        per = spec.degree if spec.kind == "cheby" else 1
        out["precond"] = _time_op(precond_once, bd, la, ga, sidx, gsrc,
                                  gval, scnt, rcnt, mstate,
                                  reps=reps) / per
    return out
