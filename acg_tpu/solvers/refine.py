"""Mixed-precision iterative refinement: f64 accuracy from f32 solves.

The reference runs strictly in f64 (``comm.h:180-183``); on TPU f64 is
software-emulated and an order of magnitude slower than f32.  This
wrapper recovers f64-quality solutions while keeping the device solve in
fast f32 (SURVEY.md section 7 "hard parts" mitigation):

    repeat (outer, on host, numpy f64):
        r = b - A x                 # true f64 residual (scipy SpMV)
        solve A dx = r in f32 on the TPU to a loose inner tolerance
        x += dx
    until ||r|| / ||r0|| < rtol  or  maxouter

Each outer pass reduces the error by roughly the inner solve's relative
accuracy (~1e-4 .. 1e-6 in f32), so a handful of passes reach 1e-12.
The outer SpMV reuses the same host CSR that builds the manufactured
solution -- the independent oracle role of ``acgsymcsrmatrix_dsymvmpi``
(``cuda/acg-cuda.c:2115``).
"""

from __future__ import annotations

import time

import numpy as np

from acg_tpu.errors import NotConvergedError
from acg_tpu.solvers.stats import SolverStats, StoppingCriteria


class RefinedSolver:
    """Iterative refinement around any inner solver with a
    ``solve(b, x0=None, criteria=..., raise_on_divergence=...)`` method
    (JaxCGSolver or DistCGSolver).

    ``inner_rtol`` is the per-pass relative tolerance of the f32 device
    solve; ``inner_maxits`` caps each pass.  Statistics accumulate the
    total inner iterations (the analog of the reference's
    ``ntotaliterations``), and ``stats.nrefine`` counts outer passes.
    """

    def __init__(self, inner, full_csr, inner_rtol: float = 1e-5,
                 inner_maxits: int | None = None, n: int | None = None,
                 nnz: int | None = None):
        """``full_csr`` may instead be a CALLABLE ``matvec(x) -> A @ x``
        in f64 (pass ``n``, and ``nnz`` for flop accounting, then): the
        distributed-read path supplies a per-part host SpMV over its
        local blocks so the outer residual never needs the full matrix
        on any controller."""
        self.inner = inner
        if callable(full_csr) and not hasattr(full_csr, "shape"):
            if n is None:
                raise ValueError("matvec form needs n")
            self._matvec = full_csr
            self._n = int(n)
            self._nnz2 = 2.0 * (nnz or 0)
        else:
            self.csr = full_csr
            self._matvec = full_csr.__matmul__
            self._n = full_csr.shape[0]
            self._nnz2 = 2.0 * full_csr.nnz
        self.inner_rtol = float(inner_rtol)
        self.inner_maxits = inner_maxits
        self.stats = SolverStats(unknowns=self._n)
        self.stats.nrefine = 0

    def solve(self, b, x0=None, criteria: StoppingCriteria | None = None,
              raise_on_divergence: bool = True,
              warmup: int = 0) -> np.ndarray:
        crit = criteria or StoppingCriteria()
        st = self.stats
        st.criteria = crit
        b = np.asarray(b, dtype=np.float64)
        x = (np.zeros_like(b) if x0 is None
             else np.asarray(x0, dtype=np.float64).copy())

        if warmup > 0:
            # compile/warm the inner program outside the timed region
            # (the direct solvers exclude warmup from tsolve the same way).
            # The warmup criteria must carry a residual tolerance: the real
            # inner passes use residual_rtol > 0 (unbounded=False), and
            # `unbounded` is a jit static argname, so an all-zero-tolerance
            # warmup would compile a *different* program variant and the
            # first timed pass would recompile inside the timed region.
            self.inner.solve(b.astype(np.float64), x0=None,
                             criteria=StoppingCriteria(
                                 maxits=1, residual_rtol=self.inner_rtol),
                             raise_on_divergence=False, warmup=warmup - 1)
            warmup = 0
        t0 = time.perf_counter()
        r = b - self._matvec(x)
        r0nrm2 = float(np.linalg.norm(r))
        st.bnrm2 = float(np.linalg.norm(b))
        st.x0nrm2 = float(np.linalg.norm(x))
        st.r0nrm2 = r0nrm2
        res_tol = max(crit.residual_atol, crit.residual_rtol * r0nrm2)
        # res_tol == 0 means no residual target (benchmark / maxits-only
        # mode): spend the iteration budget and report converged, the
        # same semantics as the direct solvers' unbounded path.  (Diff
        # criteria have no meaning across refinement passes.)
        unbounded = res_tol <= 0

        total_inner = 0
        npasses = 0
        rnrm2 = r0nrm2
        stalled = False
        inner_flops0 = self.inner.stats.nflops  # lifetime-cumulative
        converged = (not unbounded) and rnrm2 < res_tol
        # cap outer passes: each pass gains ~ -log10(inner_rtol) digits,
        # so 40 passes is far beyond any f64 target; divergence is caught
        # by the stagnation test below
        while not converged and not stalled and npasses < 40 \
                and total_inner < crit.maxits:
            # never exceed the user's total iteration cap (--max-iterations)
            budget = crit.maxits - total_inner
            inner_crit = StoppingCriteria(
                maxits=min(self.inner_maxits or budget, budget),
                residual_rtol=self.inner_rtol)
            dx = self.inner.solve(r, criteria=inner_crit,
                                  raise_on_divergence=False, warmup=warmup)
            warmup = 0  # only warm the first pass
            x_prev, rnrm2_prev = x, rnrm2
            x = x + dx
            npasses += 1
            total_inner += self.inner.stats.niterations
            r = b - self._matvec(x)
            rnrm2 = float(np.linalg.norm(r))
            if rnrm2 > rnrm2_prev:
                # diverging pass: keep the better previous iterate so the
                # reported residual describes the returned solution
                x, rnrm2 = x_prev, rnrm2_prev
                stalled = True
            elif rnrm2 >= 0.5 * rnrm2_prev:
                stalled = True  # inner f32 accuracy exhausted
            converged = (not unbounded) and rnrm2 < res_tol

        if unbounded:
            converged = True

        st.tsolve += time.perf_counter() - t0
        st.nsolves += 1
        st.nrefine = npasses
        st.niterations = total_inner
        st.ntotaliterations += total_inner
        st.rnrm2 = rnrm2
        st.dxnrm2 = float("inf")
        st.converged = bool(converged)
        st.nflops += (self.inner.stats.nflops - inner_flops0
                      + self._nnz2 * npasses)
        st.fexcept_arrays = [x]
        if not converged and raise_on_divergence:
            raise NotConvergedError(
                f"refinement stalled after {npasses} passes "
                f"({total_inner} inner iterations), residual {rnrm2:.3e}")
        return x
