"""Solver statistics and report formatting.

Rebuilds the always-on counter tier of the reference's profiling (SURVEY.md
section 5): every solver accumulates iteration counts, analytic flop/byte
totals, and per-op-class breakdowns in its struct (``cg.h:88-98``,
``cgcuda.h:107-116``) and reports them in a fixed text block
(``acgsolvercuda_fwrite``, ``cgcuda.c:1927-1975``).  The report format here
is line-compatible so the reference's analysis scripts (which grep
``total solver time``) work unchanged.

One deliberate deviation: under ``jax.jit`` the whole solve is one fused
XLA program, so per-op *times* are not separately observable in-loop.
Per-op counts and analytic bytes are always tracked; op times are filled
by the host reference solver (eager mode) and, for the compiled solvers,
by the replay-based profiling tier (:mod:`acg_tpu.solvers.profile`,
CLI ``--profile-ops``).  Use ``jax.profiler`` traces (``--trace``) for
the fine-grained tier.
"""

from __future__ import annotations

import dataclasses
import io
import sys

from acg_tpu.errors import fexcept_str

OP_CLASSES = ("gemv", "dot", "nrm2", "axpy", "copy", "allreduce", "halo",
              "precond")
# report labels match the reference output block
_OP_LABELS = {"allreduce": "MPI_Allreduce", "halo": "MPI_HaloExchange"}
# op classes the reference block does not know: their row renders only
# when something was counted, so unpreconditioned reports stay
# byte-identical to the reference's (the resilience-lines discipline)
_OPTIONAL_OPS = ("precond",)

# canonical pipeline-phase order for the ``timings:`` section (the
# telemetry tier's always-on phase timer); phases recorded out of order
# -- solvers record transfer/compile/solve, the CLI records the rest --
# still report in this order
# "ckpt" is the survivability tier's snapshot serialisation + atomic
# rename (acg_tpu.checkpoint), billed to its OWN phase so solve (and
# the soak latency histograms) never absorb checkpoint time
PHASE_ORDER = ("ingest", "partition", "transfer", "compile", "solve",
               "ckpt", "writeback")


@dataclasses.dataclass
class StoppingCriteria:
    """Stopping criteria, all four of the reference's (``cg.h:136-149``):

      * maxits - iteration cap
      * residual_atol:  ||b - Ax|| < atol
      * residual_rtol:  ||b - Ax|| / ||b - Ax0|| < rtol
      * diff_atol:      ||alpha p|| < atol   (difference in iterates)
      * diff_rtol:      ||alpha p|| / ||x|| < rtol
    A tolerance of 0 disables that criterion.
    """

    maxits: int = 100
    residual_atol: float = 0.0
    residual_rtol: float = 0.0
    diff_atol: float = 0.0
    diff_rtol: float = 0.0

    @property
    def needs_diff(self) -> bool:
        return self.diff_atol > 0 or self.diff_rtol > 0

    @property
    def unbounded(self) -> bool:
        """True when no tolerance is set: run exactly maxits iterations."""
        return (self.residual_atol == 0 and self.residual_rtol == 0
                and self.diff_atol == 0 and self.diff_rtol == 0)


@dataclasses.dataclass
class OpStats:
    n: int = 0
    t: float = 0.0
    bytes: int = 0

    def add(self, n=1, t=0.0, bytes=0):
        self.n += n
        self.t += t
        self.bytes += bytes


@dataclasses.dataclass
class SolverStats:
    """Accumulated solver state + statistics (the ``acgsolver*`` struct role)."""

    unknowns: int = 0
    nsolves: int = 0
    ntotaliterations: int = 0
    niterations: int = 0
    nflops: float = 0.0
    tsolve: float = 0.0
    bnrm2: float = 0.0
    x0nrm2: float = 0.0
    r0nrm2: float = 0.0
    rnrm2: float = 0.0
    dxnrm2: float = 0.0
    converged: bool = False
    criteria: StoppingCriteria = dataclasses.field(default_factory=StoppingCriteria)
    ops: dict = dataclasses.field(
        default_factory=lambda: {k: OpStats() for k in OP_CLASSES})
    fexcept_arrays: list = dataclasses.field(default_factory=list)
    # resilience tier (solvers.resilience): detected breakdowns,
    # host-policy restarts, and transport/solver fallbacks, with a
    # human-readable event log surfaced in the report
    nbreakdowns: int = 0
    nrestarts: int = 0
    nfallbacks: int = 0
    # survivability tier (acg_tpu.checkpoint): rollbacks to the last
    # on-disk snapshot -- the recovery ladder's new first rung
    nrollbacks: int = 0
    recovery_log: list = dataclasses.field(default_factory=list)
    # telemetry tier (acg_tpu.telemetry): timestamped resilience/fault
    # events for the structured sink, pipeline-phase seconds, and the
    # last solve's convergence trace (a telemetry.ConvergenceTrace)
    events: list = dataclasses.field(default_factory=list)
    timings: dict = dataclasses.field(default_factory=dict)
    trace: object = None
    # perfmodel tier (acg_tpu.perfmodel): the compiler's OWN cost
    # analysis of the compiled solve program (flops / bytes accessed,
    # per-iteration derivation, the static communication ledger) and its
    # memory analysis (argument/output/temp/generated-code HBM bytes).
    # Sections render only when an analysis pass (--explain) recorded
    # them -- the reference-format block stays byte-identical otherwise
    costmodel: dict = dataclasses.field(default_factory=dict)
    memory: dict = dataclasses.field(default_factory=dict)
    # service-metrics tier (acg_tpu.soak): the soak driver's report --
    # latency/iteration percentiles + drift verdict.  Rendered (and
    # exported, stats schema /3) only when a soak run recorded it
    soak: dict = dataclasses.field(default_factory=dict)
    # preconditioning tier (acg_tpu.precond, stats schema /4): the armed
    # preconditioner's kind/parameters, analytic applies, and spectral
    # estimates.  Appends after every existing section, like soak
    precond: dict = dataclasses.field(default_factory=dict)
    # numerical-health tier (acg_tpu.health, stats schema /5): in-loop
    # true-residual audit summary (gap/count/threshold) and the
    # post-hoc Lanczos spectrum estimate.  Appends strictly last
    health: dict = dataclasses.field(default_factory=dict)
    # survivability tier (acg_tpu.checkpoint, stats schema /6): the
    # armed snapshot configuration, snapshots written/resumed, and the
    # last committed iteration.  Appends after health
    ckpt: dict = dataclasses.field(default_factory=dict)
    # timeline-tracing tier (acg_tpu.tracing, stats schema /7): the
    # profiler-capture analysis (measured per-op-class seconds, overlap
    # efficiency, straggler attribution) and the --timeline export
    # summary.  Appends strictly last
    tracing: dict = dataclasses.field(default_factory=dict)
    # live-observatory tier (acg_tpu.observatory, stats schema /8): the
    # declared --slo objectives and their observation/breach/burn
    # verdict.  Appends strictly last
    slo: dict = dataclasses.field(default_factory=dict)
    # batched multi-RHS tier (acg_tpu.solvers.batched, stats schema
    # /9): nrhs, per-RHS iteration/residual/converged columns, and the
    # block-CG iteration totals.  Appends strictly last
    batch: dict = dataclasses.field(default_factory=dict)
    # decision observatory (acg_tpu.planner, stats schema /12): the
    # plan id / decision provenance of a planned solve and its
    # plan-vs-actual row (predicted vs measured s/solve + iterations,
    # misprediction ratio) -- the self-correction feedback the planner
    # consults on replan.  Appends strictly last
    plan: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        """Machine-readable twin of :meth:`fwrite` -- the ``stats`` key
        of a ``--stats-json`` document (schema versioned there).  Every
        value is plain-JSON-able; the convergence trace's records are
        identical dicts to the ``--convergence-log`` JSONL data lines,
        so the two sinks round-trip."""
        c = self.criteria
        d = {
            "unknowns": self.unknowns,
            "nsolves": self.nsolves,
            "ntotaliterations": self.ntotaliterations,
            "niterations": self.niterations,
            "nflops": self.nflops,
            "tsolve": self.tsolve,
            "bnrm2": self.bnrm2,
            "x0nrm2": self.x0nrm2,
            "r0nrm2": self.r0nrm2,
            "rnrm2": self.rnrm2,
            "dxnrm2": self.dxnrm2,
            "converged": bool(self.converged),
            "criteria": {
                "maxits": c.maxits,
                "residual_atol": c.residual_atol,
                "residual_rtol": c.residual_rtol,
                "diff_atol": c.diff_atol,
                "diff_rtol": c.diff_rtol,
            },
            "ops": {op: {"n": s.n, "t": s.t, "bytes": s.bytes}
                    for op, s in self.ops.items()},
            "fexcept": fexcept_str(*self.fexcept_arrays),
            "resilience": {
                "nbreakdowns": self.nbreakdowns,
                "nrestarts": self.nrestarts,
                "nfallbacks": self.nfallbacks,
                "nrollbacks": self.nrollbacks,
                "log": list(self.recovery_log),
            },
            "events": list(self.events),
            "timings": dict(self.timings),
            "costmodel": dict(self.costmodel),
            "memory": dict(self.memory),
            "soak": dict(self.soak),
            "precond": dict(self.precond),
            "health": dict(self.health),
            "ckpt": dict(self.ckpt),
            "tracing": dict(self.tracing),
            "slo": dict(self.slo),
            "batch": dict(self.batch),
            "plan": dict(self.plan),
        }
        if self.trace is not None:
            d["trace"] = self.trace.to_dict()
        # JSON has no Inf/NaN literal; dxnrm2 is inf when no diff
        # criterion ran
        import math
        for k in ("bnrm2", "x0nrm2", "r0nrm2", "rnrm2", "dxnrm2",
                  "nflops", "tsolve"):
            if not math.isfinite(d[k]):
                d[k] = repr(d[k])
        return d

    def fwrite(self, f=None, indent: int = 0) -> str:
        """Solver report, line-compatible with ``acgsolvercuda_fwrite``."""
        out = io.StringIO()
        pad = " " * indent
        c = self.criteria

        def p(line):
            out.write(pad + line + "\n")

        tother = self.tsolve - sum(o.t for o in self.ops.values())
        p(f"unknowns: {self.unknowns:,}")
        p(f"solves: {self.nsolves:,}")
        p(f"total iterations: {self.ntotaliterations:,}")
        p(f"total flops: {1.0e-9 * self.nflops:,.3f} Gflop")
        rate = 1.0e-9 * self.nflops / self.tsolve if self.tsolve > 0 else 0.0
        p(f"total flop rate: {rate:,.3f} Gflop/s")
        p(f"total solver time: {self.tsolve:,.6f} seconds")
        p("performance breakdown:")
        for op in OP_CLASSES:
            s = self.ops[op]
            if op in _OPTIONAL_OPS and s.n == 0:
                continue
            gbs = 1.0e-9 * s.bytes / s.t if s.t > 0 else 0.0
            label = _OP_LABELS.get(op, op)
            p(f"  {label}: {s.t:,.6f} seconds {s.n:,} times {s.bytes:,} B {gbs:,.3f} GB/s")
        p(f"  other: {tother:,.6f} seconds")
        p("last solve:")
        p("  stopping criterion:")
        p(f"    maximum iterations: {c.maxits:,}")
        p(f"    tolerance for residual: {c.residual_atol:.15g}")
        p(f"    tolerance for relative residual: {c.residual_rtol:.15g}")
        p(f"    tolerance for difference in solution iterates: {c.diff_atol:.15g}")
        p(f"    tolerance for relative difference in solution iterates: {c.diff_rtol:.15g}")
        p(f"  iterations: {self.niterations:,}")
        p(f"  right-hand side 2-norm: {self.bnrm2:.15g}")
        p(f"  initial guess 2-norm: {self.x0nrm2:.15g}")
        p(f"  initial residual 2-norm: {self.r0nrm2:.15g}")
        p(f"  residual 2-norm: {self.rnrm2:.15g}")
        p(f"  difference in solution iterates 2-norm: {self.dxnrm2:.15g}")
        p(f"  floating-point exceptions: {fexcept_str(*self.fexcept_arrays)}")
        # resilience lines appear only when something happened, so the
        # report stays byte-identical to the reference's on clean solves
        if (self.nbreakdowns or self.nrestarts or self.nfallbacks
                or self.nrollbacks):
            # the rollback count appends only when rollbacks happened,
            # so pre-survivability report consumers see the exact
            # historical line
            rb = (f", {self.nrollbacks} rollbacks" if self.nrollbacks
                  else "")
            p(f"  resilience: {self.nbreakdowns} breakdowns detected, "
              f"{self.nrestarts} restarts, {self.nfallbacks} fallbacks"
              + rb)
            for ev in self.recovery_log:
                p(f"    {ev}")
        # phase timings appear only when a phase timer ran (the CLI's
        # always-on tier sets them; library solves leave them empty), so
        # library reports stay byte-identical to the reference's
        if self.timings:
            p("timings:")
            seen = []
            for name in PHASE_ORDER:
                if name in self.timings:
                    seen.append(name)
                    p(f"  {name}: {self.timings[name]:,.6f} seconds")
            for name, secs in self.timings.items():
                if name not in seen:
                    p(f"  {name}: {secs:,.6f} seconds")
        # perfmodel sections (compiler-reported cost/memory + the comm
        # ledger) append strictly LAST, like timings: a disarmed run --
        # and every report the reference's scripts grep -- is unchanged
        if self.costmodel:
            p("costmodel:")
            _write_section(p, self.costmodel, 1)
        if self.memory:
            p("memory:")
            _write_section(p, self.memory, 1)
        if self.soak:
            p("soak:")
            _write_section(p, self.soak, 1)
        if self.precond:
            p("precond:")
            _write_section(p, self.precond, 1)
        if self.health:
            p("health:")
            _write_section(p, self.health, 1)
        if self.ckpt:
            p("ckpt:")
            _write_section(p, self.ckpt, 1)
        if self.tracing:
            p("tracing:")
            _write_section(p, self.tracing, 1)
        if self.slo:
            p("slo:")
            _write_section(p, self.slo, 1)
        if self.batch:
            p("batch:")
            _write_section(p, self.batch, 1)
        if self.plan:
            p("plan:")
            _write_section(p, self.plan, 1)
        text = out.getvalue()
        if f is not None:
            f.write(text)
        return text

    def print(self, indent: int = 0):
        self.fwrite(sys.stderr, indent)


def _write_section(p, d: dict, depth: int) -> None:
    """Generic nested renderer for the perfmodel sections: scalars one
    per line, sub-dicts indented, lists summarised by length (their full
    form lives in the --stats-json twin -- a 64-neighbour halo table
    does not belong in the text block)."""
    ind = "  " * depth
    for k, v in d.items():
        if isinstance(v, dict):
            p(f"{ind}{k}:")
            _write_section(p, v, depth + 1)
        elif isinstance(v, (list, tuple)):
            p(f"{ind}{k}: [{len(v)} entries -- see --stats-json]")
        elif isinstance(v, float):
            p(f"{ind}{k}: {v:,.6g}")
        else:
            p(f"{ind}{k}: {v}")


def cg_flops_per_iteration(nnz_full: int, n: int, pipelined: bool = False) -> float:
    """Analytic flop count per CG iteration (reference counts 3 flops per
    stored nonzero per SpMV -- symmetric entries counted twice -- and 2n per
    dot/axpy, ``cgcuda.c:812,901``)."""
    spmv = 3.0 * nnz_full
    if not pipelined:
        # t=Ap; dots: (p,t),(r,r); axpys: x,r,p
        return spmv + 2 * 2.0 * n + 3 * 2.0 * n
    # pipelined: q=Aw; dots (r,r),(w,r); 6 vector updates + scalar recurrences
    return spmv + 2 * 2.0 * n + 6 * 2.0 * n
