from acg_tpu.solvers.stats import SolverStats, StoppingCriteria  # noqa: F401
from acg_tpu.solvers.host_cg import HostCGSolver, HostDistCGSolver  # noqa: F401
from acg_tpu.solvers.resilience import RecoveryPolicy  # noqa: F401
